"""Wire codec for the serving protocol (serving/server.py + client.py).

The native tensor-RPC transport (native/rpc.py) moves ONE named ndarray
per frame; an inference request/reply carries several arrays of mixed
dtype plus metadata (model, tenant, deadline, status).  This codec packs
that bundle into a single uint8 tensor: an 8-byte little-endian header
length, a JSON header (metadata + per-array dtype/shape), then the raw
array bytes concatenated — so one ``send_var``/``get_var`` round trip
moves a whole request, and the existing framing/dedupe/retry machinery
applies unchanged.

Wire keys (PS-style __dunder__ namespace, next to ``__metrics__`` and the
elastic ``__alive__``):

  ``__infer__:<req_id>``   client -> server, packed request
                           meta: model / tenant / req_id / deadline_ms
  ``__reply__:<req_id>``   server -> client, packed reply
                           meta: status ok|shed|timeout|error,
                           retry_after_ms on shed, outputs name order
  ``__spec__:<model>``     server-published feed/fetch signature + buckets
                           (loadgen synthesizes valid feeds from it)
  ``__generate__:<id>``    autoregressive request: prompt ids array +
                           meta model / max_new_tokens / stream
  ``__stream__:<id>:<k>``  k-th generated-token chunk (meta token / i /
                           done / status); the client's parked GETs walk
                           k = 0, 1, ... until done — token-level TTFT
                           and inter-token latency fall out client-side
  ``__abort__:<id>``       client gave up (timeout replay): the decode
                           engine drops the sequence and frees its paged
                           KV blocks so an abandoned prefill can't pin
                           the pool

Control-plane keys (PR 16):

  ``__retire__``           coordinator -> replica: stop admitting, drain
                           the queue at a batch boundary, then exit (the
                           autoscaler's graceful scale-down path)
  ``__rollout__``          per-replica published rollout state (packed
                           {"models": {base: {active/canary/fraction/
                           state}}}) — the chaos leg GETs it from every
                           survivor to assert version agreement
  ``__rollout_set__``      coordinator -> replica state broadcast (same
                           payload); idempotent, re-sent periodically so
                           a replica that missed a flip converges
  ``__rollout_ctl__:<id>`` client -> coordinator admin command
                           (start/flip/abort/status); the reply lands on
                           ``__reply__:<id>`` like any request

Disaggregated prefill/decode keys (PR 17):

  ``__kvxfer__:<id>``      prefill -> decode sealed-KV-block stream, one
                           frame per sealed block plus bracketing control
                           frames, all sent on one FIFO connection so
                           arrival order == send order.  Frame kinds
                           (meta ``kind``): "expect" (req announced, arms
                           the orphan janitor), "block" (payload arrays:
                           k/v [L, block, H, D] in the pool's residency
                           dtype, plus k/v scales [L, block, H] when
                           int8; meta carries the hash-chain ``pos`` and
                           ``digest``), "commit" (full prompt + decode
                           params + prefill-side phase timings; the
                           decode replica submits from here), "cancel"
                           (prefill-side abort/shed/timeout: the decode
                           half frees any adopted blocks and publishes
                           the terminal reply).  Packed by
                           ``pack_kvxfer`` and validated LOUDLY by
                           ``unpack_kvxfer`` — a truncated frame or a
                           hash-chain position mismatch raises instead
                           of adopting garbage into the KV pool.
  ``__pair__:<req_id>``    prefill-replica-published routing hint: meta
                           {"decode": "host:port" | None}.  The client
                           GETs it right after ``__generate__`` and walks
                           ``__stream__``/``__reply__`` on the decode
                           half; None means the replica serves the
                           request itself (monolith fallback).

Live session migration keys (serving/migrate.py):

  ``__resume__:<id>``      client -> survivor crash-resume: original
                           prompt + every token already received; the
                           engine re-admits the sequence against its
                           prefix index (full-history hash chain) and
                           continues emitting at the next token index —
                           never re-emitting a token the client holds.
  ``__resumeack__:<id>``   migration destination -> source verdict for a
                           kind=session hand-off ("resumed" | an error
                           status); the source commits (frees the
                           victim's blocks, finishes it "migrated") on
                           "resumed" and falls back to local recompute
                           on anything else.

Requests carry their SLO tier in the meta under ``TIER`` ("paid" /
"free" / "batch"); the engine's deadline-weighted admission sheds
low-weight tiers first under overload, counted per tier in
``serving_tier_shed_total{tier}``.

Distributed tracing (core/tracing.py) rides the meta under the
``TRACEPARENT`` key: the client stamps its root span's W3C-style
``traceparent`` into the request meta, the server parents its admission
span under it, and the reply meta echoes it (plus per-phase timings under
``"phases"``) so one trace_id spans client and replica processes.
"""

import json

import numpy as np

__all__ = ["pack", "unpack", "pack_kvxfer", "unpack_kvxfer",
           "INFER_KEY", "REPLY_KEY", "SPEC_KEY",
           "ALIVE_KEY", "GEN_KEY", "STREAM_KEY", "ABORT_KEY",
           "RETIRE_KEY", "ROLLOUT_KEY", "ROLLOUT_SET_KEY",
           "ROLLOUT_CTL_KEY", "KVXFER_KEY", "PAIR_KEY",
           "RESUME_KEY", "RESUME_ACK_KEY",
           "TRACEPARENT", "TIER"]

INFER_KEY = "__infer__:"
REPLY_KEY = "__reply__:"
SPEC_KEY = "__spec__:"
ALIVE_KEY = "__alive__"
# autoregressive decode: request, per-token stream chunks (suffixed
# ":<index>"), and client-side abandonment (frees the paged KV blocks)
GEN_KEY = "__generate__:"
STREAM_KEY = "__stream__:"
ABORT_KEY = "__abort__:"
# serving control plane: autoscaler drain-and-exit order, rollout state
# (published per replica / broadcast by the coordinator), admin commands
RETIRE_KEY = "__retire__"
ROLLOUT_KEY = "__rollout__"
ROLLOUT_SET_KEY = "__rollout_set__"
ROLLOUT_CTL_KEY = "__rollout_ctl__:"
# disaggregated serving: sealed-KV-block transfer frames (prefill ->
# decode) and the per-request pair-routing hint the client GETs
KVXFER_KEY = "__kvxfer__:"
PAIR_KEY = "__pair__:"
# live session migration (serving/migrate.py): a crash-resume request
# (client -> survivor; arrays [prompt, tokens-already-received], meta
# model / max_new_tokens / eos_id / stream / tier) lands under
# __resume__:<req_id>; a migration destination publishes its admit/
# reject verdict under __resumeack__:<req_id> for the source to GET
# (separate key so a replica's poll loop never consumes its own ack)
RESUME_KEY = "__resume__:"
RESUME_ACK_KEY = "__resumeack__:"
# meta key carrying the W3C-style trace context across the wire
TRACEPARENT = "traceparent"
# meta key carrying the request's SLO tier (paid|free|batch)
TIER = "tier"


def pack(meta, arrays=()):
    """(meta dict, [ndarray, ...]) -> one uint8 ndarray."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    header = json.dumps({
        "meta": meta,
        "arrays": [{"dtype": a.dtype.str, "shape": list(a.shape)}
                   for a in arrays],
    }).encode("utf-8")
    parts = [len(header).to_bytes(8, "little"), header]
    parts.extend(a.tobytes() for a in arrays)
    return np.frombuffer(b"".join(parts), dtype=np.uint8).copy()


def unpack(arr):
    """Inverse of pack: uint8 ndarray -> (meta dict, [ndarray, ...])."""
    buf = np.ascontiguousarray(np.asarray(arr, dtype=np.uint8)).tobytes()
    hlen = int.from_bytes(buf[:8], "little")
    head = json.loads(buf[8:8 + hlen].decode("utf-8"))
    out, off = [], 8 + hlen
    for spec in head["arrays"]:
        dt = np.dtype(spec["dtype"])
        shape = tuple(spec["shape"])
        n = dt.itemsize * int(np.prod(shape, dtype=np.int64)) \
            if shape else dt.itemsize
        out.append(np.frombuffer(buf[off:off + n], dtype=dt)
                   .reshape(shape).copy())
        off += n
    return head["meta"], out


# -- sealed-KV-block transfer frames ------------------------------------------
#
# KV payloads are adopted straight into a decode replica's paged pool, so
# unlike the best-effort request path these frames are validated loudly:
# a frame whose byte count disagrees with its header (truncation,
# mid-write connection loss) or whose hash-chain position is not the one
# the receiver expects raises ValueError instead of quietly corrupting
# the pool.  ``kvxfer`` magic + declared payload length make both checks
# cheap and unambiguous.

_KVXFER_KINDS = ("expect", "block", "commit", "cancel", "session")


def pack_kvxfer(meta, arrays=()):
    """Pack one transfer frame.  ``meta`` must carry ``kind`` (one of
    expect|block|commit|cancel|session) and ``req_id``; block frames
    additionally ``pos`` (hash-chain block index) and ``digest`` (sha256
    hex).  A ``session`` frame carries a live-migration manifest
    (serving/migrate.py): arrays [prompt, emitted tokens] plus meta
    model / position / sealed-block digests / tail descriptor — it is
    sent LAST on the stream, after the session's block frames, so the
    receiver resumes only once every sealed block has landed."""
    kind = meta.get("kind")
    if kind not in _KVXFER_KINDS:
        raise ValueError("kvxfer frame kind must be one of %s, got %r"
                         % ("|".join(_KVXFER_KINDS), kind))
    if not meta.get("req_id"):
        raise ValueError("kvxfer frame meta wants a req_id")
    if kind == "block":
        pos = meta.get("pos")
        if not isinstance(pos, int) or pos < 0:
            raise ValueError("kvxfer block frame wants pos >= 0, got %r"
                             % (pos,))
        digest = meta.get("digest")
        if not (isinstance(digest, str) and len(digest) == 64):
            raise ValueError("kvxfer block frame wants a sha256 hex "
                             "digest, got %r" % (digest,))
    arrays = [np.ascontiguousarray(a) for a in arrays]
    m = dict(meta)
    m["kvxfer"] = 1
    m["payload_bytes"] = int(sum(a.nbytes for a in arrays))
    return pack(m, arrays)


def unpack_kvxfer(arr, expect_pos=None):
    """Inverse of pack_kvxfer with loud validation.

    Raises ValueError on anything short of a byte-exact frame: missing
    kvxfer magic, a declared payload length that disagrees with the
    actual byte count (truncated frame), or — when ``expect_pos`` is
    given — a block frame whose hash-chain ``pos`` is not the expected
    next position (out-of-order / dropped frame on the stream)."""
    buf = np.ascontiguousarray(np.asarray(arr, dtype=np.uint8)).tobytes()
    if len(buf) < 8:
        raise ValueError("kvxfer frame truncated: %d bytes is shorter "
                         "than the 8-byte header length" % len(buf))
    hlen = int.from_bytes(buf[:8], "little")
    if 8 + hlen > len(buf):
        raise ValueError("kvxfer frame truncated: header wants %d bytes,"
                         " frame holds %d" % (8 + hlen, len(buf)))
    try:
        head = json.loads(buf[8:8 + hlen].decode("utf-8"))
        meta, arrays = head["meta"], head["arrays"]
    except Exception as e:
        raise ValueError("kvxfer frame header unreadable: %s" % e)
    if meta.get("kvxfer") != 1:
        raise ValueError("not a kvxfer frame (missing kvxfer magic)")
    declared = int(meta.get("payload_bytes", -1))
    actual = len(buf) - 8 - hlen
    if declared != actual:
        raise ValueError("kvxfer frame truncated: header declares %d "
                         "payload bytes, frame holds %d"
                         % (declared, actual))
    want = 0
    for spec in arrays:
        dt = np.dtype(spec["dtype"])
        shape = tuple(spec["shape"])
        want += dt.itemsize * int(np.prod(shape, dtype=np.int64)) \
            if shape else dt.itemsize
    if want != actual:
        raise ValueError("kvxfer frame truncated: array specs want %d "
                         "bytes, frame holds %d" % (want, actual))
    if expect_pos is not None and meta.get("kind") == "block" \
            and int(meta.get("pos", -1)) != int(expect_pos):
        raise ValueError("kvxfer hash-chain position mismatch: got pos="
                         "%r, expected %d (block stream for req %s is "
                         "out of order)"
                         % (meta.get("pos"), expect_pos,
                            meta.get("req_id")))
    return unpack(arr)
