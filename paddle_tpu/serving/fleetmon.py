"""Fleet-wide metrics plane: scrape -> merge -> window -> alert (PR 18).

Every replica already republishes its local telemetry snapshot under the
``__metrics__`` RPC key once a second.  ``FleetMonitor`` is the
aggregation side: each tick it re-reads the endpoints file (so
membership changes from the autoscaler/rollout are picked up without a
restart), scrapes every live replica, and builds ONE fleet document:

  * histograms merged EXACTLY via the shared log-spaced bucket vectors
    (``telemetry.merge_hist_snapshots``) — a fleet p99 is the percentile
    of the union of all replicas' observations to within one bucket
    width, not the worst replica's local estimate;
  * windowed RATES (shed/s, tokens/s, requests/s, cache-miss/s) from
    reset-safe counter deltas over a per-endpoint history ring — a
    replica restart zeroing its counters never produces a negative or
    inflated rate;
  * multi-window BURN-RATE SLO rules: for each configured rule
    (``FLAGS_serving_slo_rules``, "name:metric:pQQ:objective_ms"
    ;-separated) the windowed percentile over a fast and a slow window
    is divided by the objective; the alert FIRES when both windows burn
    >= FLAGS_serving_slo_burn_threshold (fast window catches the step,
    slow window suppresses blips) and CLEARS with hysteresis when the
    fast burn drops below threshold x FLAGS_serving_slo_clear_ratio;
  * GOODPUT: replies/tokens that met their deadline per second, next to
    raw throughput — the gap between the two is the cost of queueing
    that raw qps hides.

The merged document is republished under the ``__fleet__`` RPC key on
the coordinator (any client can GET one doc instead of N scrapes) and
drives the two existing control consumers: the AutoScaler's default
pressure rule consumes ``autoscale_metrics()`` (fleet queue depth +
windowed shed rate instead of a one-replica instant), and the rollout
gate's ``merge_stats`` computes its canary-vs-baseline p99s from the
same merged buckets.

Everything is injectable (``scrape_fn``, ``now_fn``, explicit
``endpoints``) so the unit tests drive ``tick()`` with synthetic
snapshots and a fake clock — no sockets, no sleeps.
"""

import json
import logging
import os
import threading
import time

from ..core import telemetry as _tm

__all__ = ["FleetMonitor", "SLORule", "parse_slo_rules", "FLEET_RPC_KEY"]

FLEET_RPC_KEY = "__fleet__"


def _flag(name):
    from .. import flags

    return flags.flag(name)


def _family(flat):
    """``server_ms{tier=paid}`` -> ``server_ms`` (flat key -> family)."""
    return flat.split("{", 1)[0]


class SLORule:
    """One burn-rate rule: percentile ``quantile`` of histogram
    ``metric`` (a flat key like ``server_ms{tier=paid}`` for one label
    set, or a bare family name like ``itl_ms`` to merge every label
    set) against ``objective_ms``."""

    __slots__ = ("name", "metric", "quantile", "objective_ms")

    def __init__(self, name, metric, quantile, objective_ms):
        self.name = name
        self.metric = metric
        self.quantile = float(quantile)
        self.objective_ms = float(objective_ms)

    def matches(self, flat):
        if "{" in self.metric:
            return flat == self.metric
        return _family(flat) == self.metric

    def as_dict(self):
        return {"name": self.name, "metric": self.metric,
                "quantile": self.quantile,
                "objective_ms": self.objective_ms}


def parse_slo_rules(spec=None):
    """``FLAGS_serving_slo_rules`` syntax:
    ``name:metric:pQQ:objective_ms`` joined by ``;`` — e.g.
    ``paid_server:server_ms{tier=paid}:p99:500;decode_itl:itl_ms:p99:250``.
    Malformed entries are skipped with a warning (a typo in one rule
    must not take down the whole monitor)."""
    spec = spec if spec is not None else _flag("serving_slo_rules")
    rules = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) != 4 or not fields[2].startswith("p"):
            logging.warning("[fleetmon] skipping malformed SLO rule %r "
                            "(want name:metric:pQQ:objective_ms)", part)
            continue
        try:
            q = float(fields[2][1:]) / 100.0
            rules.append(SLORule(fields[0], fields[1], q,
                                 float(fields[3])))
        except ValueError:
            logging.warning("[fleetmon] skipping malformed SLO rule %r",
                            part)
    return rules


def _read_endpoints_doc(path):
    """The fleet's atomic endpoints file ->
    (endpoints, {endpoint: role}, epoch).  (client.read_endpoints_doc
    returns the client-routing shape; this one keys roles by endpoint
    and carries the epoch.)"""
    with open(path) as f:
        doc = json.load(f)
    eps = list(doc.get("endpoints") or [])
    roles = doc.get("roles") or []
    role_of = {ep: (roles[i] if i < len(roles) else "serve")
               for i, ep in enumerate(eps)}
    return eps, role_of, int(doc.get("epoch", 0))


class FleetMonitor:
    """Scrape/merge/alert loop.  Construct with either a live wiring
    (``server`` + ``fleet`` and/or ``endpoints_file``) or a test wiring
    (explicit ``endpoints`` + ``scrape_fn`` + ``now_fn``) and drive via
    ``start()`` or direct ``tick()`` calls."""

    def __init__(self, server=None, fleet=None, endpoints_file=None,
                 endpoints=None, interval_s=None, rate_window_s=None,
                 fast_window_s=None, slow_window_s=None,
                 burn_threshold=None, clear_ratio=None, rules=None,
                 scrape_fn=None, now_fn=None):
        self.server = server
        self.fleet = fleet
        self.endpoints_file = endpoints_file or \
            _flag("serving_endpoints_file") or None
        self.static_endpoints = list(endpoints) if endpoints else None
        self.interval_s = float(
            interval_s if interval_s is not None
            else _flag("serving_fleetmon_interval"))
        self.rate_window_s = float(
            rate_window_s if rate_window_s is not None
            else _flag("serving_rate_window"))
        self.fast_window_s = float(
            fast_window_s if fast_window_s is not None
            else _flag("serving_slo_fast_window"))
        self.slow_window_s = float(
            slow_window_s if slow_window_s is not None
            else _flag("serving_slo_slow_window"))
        self.burn_threshold = float(
            burn_threshold if burn_threshold is not None
            else _flag("serving_slo_burn_threshold"))
        self.clear_ratio = float(
            clear_ratio if clear_ratio is not None
            else _flag("serving_slo_clear_ratio"))
        self.rules = rules if rules is not None else parse_slo_rules()
        self._scrape = scrape_fn or \
            (lambda ep: _tm.scrape(ep, timeout=3.0))
        self._now = now_fn or time.time
        # per-endpoint history ring: [(t, {"counters": {flat: v},
        # "hists": {flat: cumulative-buckets}})] — windowed rates and
        # windowed bucket-delta percentiles both read from here
        self._rings = {}
        self._roles = {}
        self.alert_state = {r.name: False for r in self.rules}
        self.last = None              # last fleet doc (tick output)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    # -- membership ----------------------------------------------------------

    def _is_coordinator(self):
        return self.fleet is None or self.fleet.is_coordinator()

    def _endpoints(self):
        """(endpoints, {endpoint: role}, epoch) for this tick — the
        endpoints file wins (it is the fleet's published truth and this
        re-read is what makes membership changes visible without a
        monitor restart), then the live fleet view, then the static
        test list."""
        if self.endpoints_file and os.path.exists(self.endpoints_file):
            try:
                return _read_endpoints_doc(self.endpoints_file)
            except (OSError, ValueError):
                pass                   # torn/missing file: fall through
        if self.fleet is not None:
            eps = [self.fleet.endpoints[r]
                   for r in sorted(self.fleet.live)]
            roles = {self.fleet.endpoints[r]: self.fleet.role_of(r)
                     for r in sorted(self.fleet.live)}
            return eps, roles, self.fleet.epoch
        eps = self.static_endpoints or []
        return eps, {ep: "serve" for ep in eps}, 0

    # -- ring math -----------------------------------------------------------

    def _record(self, ep, now, snap):
        ring = self._rings.setdefault(ep, [])
        ring.append((now, {
            "counters": dict(snap.get("counters") or {}),
            "hists": {flat: list(h.get("buckets") or [])
                      for flat, h in (snap.get("histograms")
                                      or {}).items()},
        }))
        # keep the slow window plus one pre-cut baseline sample
        cut = now - self.slow_window_s
        while len(ring) > 2 and ring[1][0] < cut:
            ring.pop(0)

    def _windowed_cum(self, ep, flat, now, window_s):
        """Cumulative bucket vector of ``flat``'s observations on ``ep``
        within the trailing window: the elementwise difference of two
        cumulative snapshots IS the window's cumulative vector.  A
        negative element means the replica restarted mid-window — the
        post-reset vector stands alone (Prometheus counter-reset
        rule)."""
        ring = self._rings.get(ep) or []
        pts = [(t, rec["hists"].get(flat)) for t, rec in ring]
        pts = [(t, v) for t, v in pts if v]
        if not pts:
            return None
        cut = now - window_s
        inside = [i for i, (t, _) in enumerate(pts) if t >= cut]
        if not inside:
            return None
        cur = pts[inside[-1]][1]
        base_i = inside[0] - 1
        if base_i < 0:
            return [int(c) for c in cur]
        base = pts[base_i][1]
        if len(base) != len(cur):
            return [int(c) for c in cur]
        delta = [int(c) - int(b) for c, b in zip(cur, base)]
        if any(d < 0 for d in delta):
            return [int(c) for c in cur]
        return delta

    def _rate(self, ep, flat, now, window_s=None):
        ring = self._rings.get(ep) or []
        pts = [(t, rec["counters"].get(flat, 0.0)) for t, rec in ring]
        return _tm.rate_from_samples(
            pts, window_s or self.rate_window_s, now=now)

    def windowed_percentile(self, rule, now, window_s, endpoints=None):
        """Fleet percentile of ``rule.metric`` over the trailing window:
        per-endpoint windowed cumulative vectors (all matching label
        sets) sum elementwise, then ``bucket_percentile``.  Returns
        (value_ms, observations)."""
        eps = endpoints if endpoints is not None else list(self._rings)
        merged = None
        for ep in eps:
            ring = self._rings.get(ep)
            if not ring:
                continue
            for flat in ring[-1][1]["hists"]:
                if not rule.matches(flat):
                    continue
                cum = self._windowed_cum(ep, flat, now, window_s)
                if cum is None:
                    continue
                if merged is None:
                    merged = list(cum)
                elif len(merged) == len(cum):
                    merged = [a + b for a, b in zip(merged, cum)]
        if not merged or merged[-1] <= 0:
            return 0.0, 0
        return _tm.bucket_percentile(merged, rule.quantile), \
            int(merged[-1])

    # -- one tick ------------------------------------------------------------

    def tick(self, now=None):
        """Scrape every live replica, rebuild the fleet doc, update burn
        gauges/alerts, republish.  Returns the doc (tests read it
        directly; ``self.last`` keeps it for the autoscaler)."""
        now = float(now if now is not None else self._now())
        eps, role_of, epoch = self._endpoints()
        snaps, rows = {}, []
        for ep in eps:
            try:
                snaps[ep] = self._scrape(ep)
            except Exception:
                _tm.inc("fleet_scrape_errors_total")
                continue
            self._record(ep, now, snaps[ep])
            self._roles[ep] = role_of.get(ep, "serve")
        # drop rings for endpoints no longer published (retired replicas
        # must not keep contributing stale windowed counts)
        for ep in list(self._rings):
            if ep not in role_of:
                self._rings.pop(ep, None)
                self._roles.pop(ep, None)
        rates = {}
        for ep in snaps:
            for flat in self._rings[ep][-1][1]["counters"]:
                rates[flat] = rates.get(flat, 0.0) + \
                    self._rate(ep, flat, now)
        merged_hists = self._merge_hists(snaps)
        counters = {}
        for snap in snaps.values():
            for flat, v in (snap.get("counters") or {}).items():
                counters[flat] = counters.get(flat, 0.0) + float(v)
        for ep in eps:
            rows.append(self._row(ep, role_of.get(ep, "serve"),
                                  snaps.get(ep)))
        doc = {
            "t": now,
            "epoch": epoch,
            "interval_s": self.interval_s,
            "rate_window_s": self.rate_window_s,
            "replicas": rows,
            "replicas_up": len(snaps),
            "histograms": merged_hists,
            "counters": counters,
            "rates": {k: round(v, 6) for k, v in rates.items()},
            "goodput": self._goodput(rates),
            "slo": self._eval_slo(now),
            "bucket_bounds": list(_tm.HIST_BUCKET_BOUNDS),
        }
        with self._lock:
            self.last = doc
        _tm.set_gauge("fleet_replicas_up", len(snaps))
        self._publish(doc)
        return doc

    def _merge_hists(self, snaps):
        keys = set()
        for snap in snaps.values():
            keys.update((snap.get("histograms") or {}))
        out = {}
        for flat in sorted(keys):
            out[flat] = _tm.merge_hist_snapshots(
                [(s.get("histograms") or {}).get(flat)
                 for s in snaps.values()])
        return out

    def _row(self, ep, role, snap):
        row = {"endpoint": ep, "role": role, "up": snap is not None}
        if snap is None:
            return row
        gauges = snap.get("gauges") or {}
        hists = snap.get("histograms") or {}

        def gmax(family):
            vals = [v for flat, v in gauges.items()
                    if _family(flat) == family]
            return max(vals) if vals else 0.0

        def p99(family):
            vals = [h.get("p99", 0.0) for flat, h in hists.items()
                    if _family(flat) == family]
            return max(vals) if vals else 0.0

        fill = [h for flat, h in hists.items()
                if _family(flat) == "serving_batch_fill"]
        row.update({
            "queue_depth": gauges.get("serving_queue_depth", 0.0),
            "batch_fill_p50": max([h.get("p50", 0.0) for h in fill]
                                  or [0.0]),
            "kv_occupancy": gmax("kv_pool_occupancy"),
            "prefix_hit_rate": gmax("prefix_cache_hit_rate"),
            "p99_ms": {f: p99(f) for f in ("server_ms", "ttft_ms",
                                           "itl_ms",
                                           "serving_execute_ms")},
            "shed_total": sum(
                v for flat, v in (snap.get("counters") or {}).items()
                if _family(flat) == "serving_shed_total"),
        })
        return row

    def _goodput(self, rates):
        def fam(name):
            return sum(v for flat, v in rates.items()
                       if _family(flat) == name)

        return {
            "replies_per_s": round(fam("serving_deadline_met_total"), 6),
            "raw_replies_per_s": round(fam("serving_requests_total"), 6),
            "missed_per_s": round(fam("serving_deadline_missed_total"),
                                  6),
            "tokens_per_s": round(fam("serving_deadline_tokens_total"),
                                  6),
            "raw_tokens_per_s": round(
                fam("serving_tokens_generated_total"), 6),
        }

    def _eval_slo(self, now):
        """Multi-window burn per rule + fire/clear hysteresis.  Burn =
        windowed percentile / objective; fire needs BOTH windows hot
        (fast catches the regression quickly, slow proves it is not a
        blip); clear needs the fast burn safely below threshold."""
        out = []
        for rule in self.rules:
            fast_p, fast_n = self.windowed_percentile(
                rule, now, self.fast_window_s)
            slow_p, slow_n = self.windowed_percentile(
                rule, now, self.slow_window_s)
            burn_fast = fast_p / rule.objective_ms
            burn_slow = slow_p / rule.objective_ms
            _tm.set_gauge("slo_burn_rate", burn_fast, slo=rule.name,
                          window="fast")
            _tm.set_gauge("slo_burn_rate", burn_slow, slo=rule.name,
                          window="slow")
            active = self.alert_state.get(rule.name, False)
            if not active and fast_n > 0 \
                    and burn_fast >= self.burn_threshold \
                    and burn_slow >= self.burn_threshold:
                active = True
                _tm.inc("slo_alerts_total", slo=rule.name, event="fire")
                _tm.event("slo_alert", slo=rule.name, event="fire",
                          burn_fast=round(burn_fast, 4),
                          burn_slow=round(burn_slow, 4))
                logging.warning(
                    "[fleetmon] SLO %s FIRING: %s %s=%.1fms burn "
                    "fast=%.2f slow=%.2f (objective %.0fms)", rule.name,
                    rule.metric, "p%d" % round(rule.quantile * 100),
                    fast_p, burn_fast, burn_slow, rule.objective_ms)
            elif active and burn_fast < \
                    self.burn_threshold * self.clear_ratio:
                active = False
                _tm.inc("slo_alerts_total", slo=rule.name, event="clear")
                _tm.event("slo_alert", slo=rule.name, event="clear",
                          burn_fast=round(burn_fast, 4))
                logging.warning("[fleetmon] SLO %s cleared (fast burn "
                                "%.2f)", rule.name, burn_fast)
            self.alert_state[rule.name] = active
            _tm.set_gauge("slo_alert_active", 1.0 if active else 0.0,
                          slo=rule.name)
            d = rule.as_dict()
            d.update({"burn_fast": round(burn_fast, 4),
                      "burn_slow": round(burn_slow, 4),
                      "p_fast_ms": round(fast_p, 3),
                      "p_slow_ms": round(slow_p, 3),
                      "samples_fast": fast_n, "samples_slow": slow_n,
                      "active": active})
            out.append(d)
        return out

    def _publish(self, doc):
        """Republish the fleet doc under ``__fleet__`` on this process's
        RPC server (coordinator only — followers still aggregate for
        their local autoscaler view but do not claim the fleet key)."""
        if self.server is None or not self._is_coordinator():
            return
        try:
            import numpy as np

            buf = json.dumps(doc, default=str).encode("utf-8")
            rpc = getattr(self.server, "rpc", self.server)
            rpc.set_var(FLEET_RPC_KEY,
                        np.frombuffer(buf, dtype=np.uint8).copy())
        except Exception:
            pass                       # server shutting down under us

    # -- control-plane consumers ---------------------------------------------

    def autoscale_metrics(self, role=None):
        """The AutoScaler's ``metrics_fn`` view, sourced from the LAST
        fleet doc: fleet-summed queue depth (optionally one role's),
        lifetime shed total, and — the windowed upgrade over the
        one-tick shed delta — shed/s over the rate window.  Returns
        None when no doc exists yet (caller falls back to local
        instants)."""
        with self._lock:
            doc = self.last
        if doc is None:
            return None
        rows = [r for r in doc["replicas"]
                if r.get("up") and (role is None or r["role"] == role)]
        eps = [r["endpoint"] for r in rows]
        now = doc["t"]
        shed_rate = sum(
            self._rate(ep, flat, now)
            for ep in eps
            for flat in ((self._rings.get(ep) or [(0, {"counters": {}})])
                         [-1][1]["counters"])
            if _family(flat) == "serving_shed_total")
        return {
            "queue_depth": sum(r.get("queue_depth", 0.0) for r in rows),
            "shed_total": sum(r.get("shed_total", 0.0) for r in rows),
            "shed_rate": shed_rate,
            "kv_occupancy": max([r.get("kv_occupancy", 0.0)
                                 for r in rows] or [0.0]),
            "replicas_up": len(rows),
        }

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:
                    logging.exception("[fleetmon] tick failed")

        self._thread = threading.Thread(target=loop, name="fleetmon",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
