"""Tiny pure-JAX transformer decoder for the autoregressive serving path.

The encoder serving stack (PR 10) runs Program-built models through
``AnalysisPredictor``; autoregressive decode instead needs a *step
function over a donated KV carry* — one token in, one token out, cache
updated in place on device.  Threading per-token cache scatters through
the Program op set would rebuild half an interpreter for no modeling
win, so the decode scenario carries its own minimal decoder (pre-LN
transformer: embed + learned positions, per-layer MHA + GELU MLP, tied
vocab head kept separate for clarity) and plugs into the SAME executor
machinery the Program path uses: ``core.executor.CarriedStepFn`` AOT-
compiles the step per lane bucket with tier-B disk persistence, and the
attention gather runs through the probe-gated
``pallas_kernels.paged_attention`` funnel.

Two step builders share every layer of math through one ``attend``
callback:

* ``make_paged_step``   — writes this token's K/V into the paged cache
  (block ids steered by the per-lane block table) and attends through
  ``paged_attention`` over the block pool.
* ``make_unpaged_step`` — the reference: contiguous per-lane K/V
  ``[L, B, S, H, D]`` updated at ``pos`` and attended via the same
  ``masked_attention`` core.

Because both paths feed bitwise-identical K/V values into the identical
attention/MLP expressions at identical shapes, paged decode is
bitwise-equal to the unpaged loop on the CPU tier — the acceptance bar
``unpaged_generate`` exists to prove.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..pallas_kernels.paged_attention import masked_attention, \
    paged_attention
from . import kv_cache as _kv

__all__ = ["DecoderConfig", "init_decoder_params", "save_decoder",
           "load_decoder", "is_decoder_dir", "make_paged_step",
           "make_unpaged_step", "unpaged_generate"]


class DecoderConfig:
    __slots__ = ("vocab", "layers", "heads", "head_dim", "ffn", "max_seq")

    def __init__(self, vocab, layers, heads, head_dim, ffn=None,
                 max_seq=64):
        self.vocab = int(vocab)
        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.ffn = int(ffn if ffn is not None else 4 * heads * head_dim)
        self.max_seq = int(max_seq)

    @property
    def hidden(self):
        return self.heads * self.head_dim

    def to_dict(self):
        return {s: getattr(self, s) for s in self.__slots__}


def init_decoder_params(cfg, seed=0):
    """name -> np.float32 array; 0.02-normal weights, identity LN."""
    r = np.random.RandomState(seed)
    h, f, v = cfg.hidden, cfg.ffn, cfg.vocab

    def w(*shape):
        return (r.standard_normal(shape) * 0.02).astype(np.float32)

    p = {"embed": w(v, h), "pos_embed": w(cfg.max_seq, h),
         "lnf_g": np.ones(h, np.float32), "lnf_b": np.zeros(h, np.float32),
         "head": w(h, v)}
    for l in range(cfg.layers):
        p.update({
            "l%d_ln1_g" % l: np.ones(h, np.float32),
            "l%d_ln1_b" % l: np.zeros(h, np.float32),
            "l%d_wq" % l: w(h, h), "l%d_wk" % l: w(h, h),
            "l%d_wv" % l: w(h, h), "l%d_wo" % l: w(h, h),
            "l%d_ln2_g" % l: np.ones(h, np.float32),
            "l%d_ln2_b" % l: np.zeros(h, np.float32),
            "l%d_w1" % l: w(h, f), "l%d_b1" % l: np.zeros(f, np.float32),
            "l%d_w2" % l: w(f, h), "l%d_b2" % l: np.zeros(h, np.float32),
        })
    return p


def save_decoder(dirname, cfg, params):
    """params.npz + decoder.json under `dirname` (tools/serve.py loads
    decode models from such a dir)."""
    os.makedirs(dirname, exist_ok=True)
    np.savez(os.path.join(dirname, "params.npz"), **params)
    with open(os.path.join(dirname, "decoder.json"), "w") as fp:
        json.dump(cfg.to_dict(), fp, indent=1, sort_keys=True)
    return dirname


def load_decoder(dirname):
    with open(os.path.join(dirname, "decoder.json")) as fp:
        cfg = DecoderConfig(**json.load(fp))
    with np.load(os.path.join(dirname, "params.npz")) as z:
        params = {k: z[k] for k in z.files}
    return cfg, params


def is_decoder_dir(dirname):
    return os.path.exists(os.path.join(dirname, "decoder.json"))


# -- shared forward ----------------------------------------------------------

def _ln(x, g, b):
    m = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - m), axis=-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(var + 1e-5) * g + b


def _token_logits(params, cfg, tok, pos, attend):
    """One token per lane through every layer; ``attend(l, q, k, v)``
    owns the KV write + history attention (the only paged/unpaged
    difference)."""
    bb = tok.shape[0]
    x = jnp.take(params["embed"], tok, axis=0) \
        + jnp.take(params["pos_embed"], pos, axis=0)
    for l in range(cfg.layers):
        def p(n, _l=l):
            return params["l%d_%s" % (_l, n)]

        h = _ln(x, p("ln1_g"), p("ln1_b"))
        q = (h @ p("wq")).reshape(bb, cfg.heads, cfg.head_dim)
        k = (h @ p("wk")).reshape(bb, cfg.heads, cfg.head_dim)
        v = (h @ p("wv")).reshape(bb, cfg.heads, cfg.head_dim)
        a = attend(l, q, k, v).reshape(bb, cfg.hidden)
        x = x + a @ p("wo")
        h2 = _ln(x, p("ln2_g"), p("ln2_b"))
        x = x + jax.nn.gelu(h2 @ p("w1") + p("b1")) @ p("w2") + p("b2")
    x = _ln(x, params["lnf_g"], params["lnf_b"])
    return x @ params["head"]


# -- paged step --------------------------------------------------------------

def make_paged_step(cfg, kv_config):
    """-> step(kv_carry, params, tok, pos, block_tables, context_lens)
    returning (new_kv_carry, next_tokens, logits).

    All shapes are static per lane bucket: tok/pos/context_lens [B],
    block_tables [B, MAXB].  ``context_lens[b]`` counts the tokens valid
    AFTER this step's write (pos + 1 for live lanes, 0 for idle lanes,
    whose table points at the reserved scratch block 0)."""
    bs = kv_config.block_size
    int8 = kv_config.dtype == "int8"

    def step(kv_carry, params, tok, pos, block_tables, context_lens):
        tok = tok.astype(jnp.int32)
        pos = pos.astype(jnp.int32)
        block_tables = block_tables.astype(jnp.int32)
        context_lens = context_lens.astype(jnp.int32)
        blk_ids = jnp.take_along_axis(
            jnp.maximum(block_tables, 0), (pos // bs)[:, None], axis=1)[:, 0]
        offs = pos % bs
        if int8:
            k_c, v_c, k_s, v_s = kv_carry
        else:
            k_c, v_c = kv_carry

        def attend(l, q, k, v):
            nonlocal k_c, v_c
            if not int8:
                k_c = k_c.at[l, blk_ids, offs].set(k)
                v_c = v_c.at[l, blk_ids, offs].set(v)
                return paged_attention(q, k_c[l], v_c[l], block_tables,
                                       context_lens)
            nonlocal k_s, v_s
            qk, sk = _kv.quantize_kv(k)
            qv, sv = _kv.quantize_kv(v)
            k_c = k_c.at[l, blk_ids, offs].set(qk)
            v_c = v_c.at[l, blk_ids, offs].set(qv)
            k_s = k_s.at[l, blk_ids, offs].set(sk)
            v_s = v_s.at[l, blk_ids, offs].set(sv)
            idx = jnp.maximum(block_tables, 0)
            bb, maxb = block_tables.shape
            kk = _kv.dequantize_kv(jnp.take(k_c[l], idx, axis=0),
                                   jnp.take(k_s[l], idx, axis=0))
            vv = _kv.dequantize_kv(jnp.take(v_c[l], idx, axis=0),
                                   jnp.take(v_s[l], idx, axis=0))
            kk = kk.reshape(bb, maxb * bs, cfg.heads, cfg.head_dim)
            vv = vv.reshape(bb, maxb * bs, cfg.heads, cfg.head_dim)
            return masked_attention(q, kk, vv, context_lens)

        logits = _token_logits(params, cfg, tok, pos, attend)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        carry = (k_c, v_c, k_s, v_s) if int8 else (k_c, v_c)
        return carry, nxt, logits

    return step


# -- unpaged reference -------------------------------------------------------

def make_unpaged_step(cfg, pad_len):
    """Reference step over contiguous per-lane K/V [L, B, pad_len, H, D].
    Same ``masked_attention`` core at the same [B, pad_len, H, D] shapes
    as the paged gather path — the bitwise comparison target."""

    def step(kv_carry, params, tok, pos, context_lens):
        tok = tok.astype(jnp.int32)
        pos = pos.astype(jnp.int32)
        context_lens = context_lens.astype(jnp.int32)
        k_c, v_c = kv_carry
        lanes = jnp.arange(k_c.shape[1], dtype=jnp.int32)

        def attend(l, q, k, v):
            nonlocal k_c, v_c
            k_c = k_c.at[l, lanes, pos].set(k)
            v_c = v_c.at[l, lanes, pos].set(v)
            return masked_attention(q, k_c[l], v_c[l], context_lens)

        logits = _token_logits(params, cfg, tok, pos, attend)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (k_c, v_c), nxt, logits

    return step


def unpaged_generate(cfg, params, prompt_ids, max_new, pad_len=None,
                     eos_id=-1, return_logits=False):
    """Greedy single-sequence reference loop (no paging, no batching):
    feed the prompt one token per step, then decode ``max_new`` tokens.
    ``pad_len`` must match the paged path's gathered history length
    (MAXB * block_size) for the bitwise comparison."""
    if pad_len is None:
        pad_len = cfg.max_seq
    step = jax.jit(make_unpaged_step(cfg, pad_len), donate_argnums=(0,))
    jparams = {k: jnp.asarray(v) for k, v in params.items()}
    kv = (jnp.zeros((cfg.layers, 1, pad_len, cfg.heads, cfg.head_dim),
                    jnp.float32),
          jnp.zeros((cfg.layers, 1, pad_len, cfg.heads, cfg.head_dim),
                    jnp.float32))
    prompt_ids = [int(t) for t in prompt_ids]
    out, logits_hist = [], []
    tok = prompt_ids[0]
    pos = 0
    while len(out) < max_new:
        kv, nxt, logits = step(
            kv, jparams, jnp.asarray([tok], jnp.int32),
            jnp.asarray([pos], jnp.int32),
            jnp.asarray([pos + 1], jnp.int32))
        pos += 1
        if pos < len(prompt_ids):
            tok = prompt_ids[pos]          # still feeding the prompt
            continue
        tok = int(nxt[0])
        out.append(tok)
        if return_logits:
            logits_hist.append(np.asarray(logits[0]))
        if tok == eos_id:
            break
    return (out, logits_hist) if return_logits else out
