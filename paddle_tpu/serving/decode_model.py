"""Tiny pure-JAX transformer decoder for the autoregressive serving path.

The encoder serving stack (PR 10) runs Program-built models through
``AnalysisPredictor``; autoregressive decode instead needs a *step
function over a donated KV carry* — one token in, one token out, cache
updated in place on device.  Threading per-token cache scatters through
the Program op set would rebuild half an interpreter for no modeling
win, so the decode scenario carries its own minimal decoder (pre-LN
transformer: embed + learned positions, per-layer MHA + GELU MLP, tied
vocab head kept separate for clarity) and plugs into the SAME executor
machinery the Program path uses: ``core.executor.CarriedStepFn`` AOT-
compiles the step per lane bucket with tier-B disk persistence, and the
attention gather runs through the probe-gated
``pallas_kernels.paged_attention`` funnel.

Two step builders share every layer of math through one ``attend``
callback:

* ``make_paged_step``   — writes this token's K/V into the paged cache
  (block ids steered by the per-lane block table) and attends through
  ``paged_attention`` over the block pool.
* ``make_unpaged_step`` — the reference: contiguous per-lane K/V
  ``[L, B, S, H, D]`` updated at ``pos`` and attended via the same
  ``masked_attention`` core.

Because both paths feed bitwise-identical K/V values into the identical
attention/MLP expressions at identical shapes, paged decode is
bitwise-equal to the unpaged loop on the CPU tier — the acceptance bar
``unpaged_generate`` exists to prove.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..pallas_kernels.paged_attention import masked_attention, \
    paged_attention
from . import kv_cache as _kv

__all__ = ["DecoderConfig", "init_decoder_params", "save_decoder",
           "load_decoder", "is_decoder_dir", "has_draft", "load_draft",
           "truncate_decoder", "make_paged_step", "make_paged_step_multi",
           "make_draft_rollout", "make_unpaged_step", "unpaged_generate"]


class DecoderConfig:
    __slots__ = ("vocab", "layers", "heads", "head_dim", "ffn", "max_seq")

    def __init__(self, vocab, layers, heads, head_dim, ffn=None,
                 max_seq=64):
        self.vocab = int(vocab)
        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.ffn = int(ffn if ffn is not None else 4 * heads * head_dim)
        self.max_seq = int(max_seq)

    @property
    def hidden(self):
        return self.heads * self.head_dim

    def to_dict(self):
        return {s: getattr(self, s) for s in self.__slots__}


def init_decoder_params(cfg, seed=0):
    """name -> np.float32 array; 0.02-normal weights, identity LN."""
    r = np.random.RandomState(seed)
    h, f, v = cfg.hidden, cfg.ffn, cfg.vocab

    def w(*shape):
        return (r.standard_normal(shape) * 0.02).astype(np.float32)

    p = {"embed": w(v, h), "pos_embed": w(cfg.max_seq, h),
         "lnf_g": np.ones(h, np.float32), "lnf_b": np.zeros(h, np.float32),
         "head": w(h, v)}
    for l in range(cfg.layers):
        p.update({
            "l%d_ln1_g" % l: np.ones(h, np.float32),
            "l%d_ln1_b" % l: np.zeros(h, np.float32),
            "l%d_wq" % l: w(h, h), "l%d_wk" % l: w(h, h),
            "l%d_wv" % l: w(h, h), "l%d_wo" % l: w(h, h),
            "l%d_ln2_g" % l: np.ones(h, np.float32),
            "l%d_ln2_b" % l: np.zeros(h, np.float32),
            "l%d_w1" % l: w(h, f), "l%d_b1" % l: np.zeros(f, np.float32),
            "l%d_w2" % l: w(f, h), "l%d_b2" % l: np.zeros(h, np.float32),
        })
    return p


def save_decoder(dirname, cfg, params, draft=None):
    """params.npz + decoder.json under `dirname` (tools/serve.py loads
    decode models from such a dir).  ``draft`` — an optional
    (DecoderConfig, params) pair — lands as a nested bundle under
    ``<dirname>/draft`` so the speculative-decode draft ships beside its
    target and the two can never drift apart."""
    os.makedirs(dirname, exist_ok=True)
    np.savez(os.path.join(dirname, "params.npz"), **params)
    with open(os.path.join(dirname, "decoder.json"), "w") as fp:
        json.dump(cfg.to_dict(), fp, indent=1, sort_keys=True)
    if draft is not None:
        dcfg, dparams = draft
        if dcfg.vocab != cfg.vocab:
            raise ValueError("draft vocab %d != target vocab %d"
                             % (dcfg.vocab, cfg.vocab))
        save_decoder(os.path.join(dirname, "draft"), dcfg, dparams)
    return dirname


def load_decoder(dirname):
    with open(os.path.join(dirname, "decoder.json")) as fp:
        cfg = DecoderConfig(**json.load(fp))
    with np.load(os.path.join(dirname, "params.npz")) as z:
        params = {k: z[k] for k in z.files}
    return cfg, params


def is_decoder_dir(dirname):
    return os.path.exists(os.path.join(dirname, "decoder.json"))


def has_draft(dirname):
    return is_decoder_dir(os.path.join(dirname, "draft"))


def load_draft(dirname):
    """The bundled draft decoder, or None when the target ships alone."""
    return load_decoder(os.path.join(dirname, "draft")) \
        if has_draft(dirname) else None


def truncate_decoder(cfg, params, layers=1):
    """A cheap draft from a target: keep the first ``layers`` transformer
    layers plus the embeddings / final LN / head verbatim.  With the
    residual stream dominated by the embedding, the truncated argmax
    tracks the full model's closely — a distillation-free draft for
    demos and smokes (real deployments train one)."""
    layers = min(int(layers), cfg.layers)
    dcfg = DecoderConfig(vocab=cfg.vocab, layers=layers, heads=cfg.heads,
                         head_dim=cfg.head_dim, ffn=cfg.ffn,
                         max_seq=cfg.max_seq)
    keep = {"embed", "pos_embed", "lnf_g", "lnf_b", "head"}
    dparams = {k: np.asarray(v) for k, v in params.items()
               if k in keep or (k.startswith("l")
                                and int(k[1:k.index("_")]) < layers)}
    return dcfg, dparams


# -- shared forward ----------------------------------------------------------

def _ln(x, g, b):
    m = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - m), axis=-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(var + 1e-5) * g + b


def _token_logits(params, cfg, tok, pos, attend):
    """One token per lane through every layer; ``attend(l, q, k, v)``
    owns the KV write + history attention (the only paged/unpaged
    difference)."""
    bb = tok.shape[0]
    x = jnp.take(params["embed"], tok, axis=0) \
        + jnp.take(params["pos_embed"], pos, axis=0)
    for l in range(cfg.layers):
        def p(n, _l=l):
            return params["l%d_%s" % (_l, n)]

        h = _ln(x, p("ln1_g"), p("ln1_b"))
        q = (h @ p("wq")).reshape(bb, cfg.heads, cfg.head_dim)
        k = (h @ p("wk")).reshape(bb, cfg.heads, cfg.head_dim)
        v = (h @ p("wv")).reshape(bb, cfg.heads, cfg.head_dim)
        a = attend(l, q, k, v).reshape(bb, cfg.hidden)
        x = x + a @ p("wo")
        h2 = _ln(x, p("ln2_g"), p("ln2_b"))
        x = x + jax.nn.gelu(h2 @ p("w1") + p("b1")) @ p("w2") + p("b2")
    x = _ln(x, params["lnf_g"], params["lnf_b"])
    return x @ params["head"]


# -- paged step --------------------------------------------------------------

def make_paged_step(cfg, kv_config):
    """-> step(kv_carry, params, tok, pos, block_tables, context_lens)
    returning (new_kv_carry, next_tokens, logits).

    All shapes are static per lane bucket: tok/pos/context_lens [B],
    block_tables [B, MAXB].  ``context_lens[b]`` counts the tokens valid
    AFTER this step's write (pos + 1 for live lanes, 0 for idle lanes,
    whose table points at the reserved scratch block 0).

    Feed-planning contract (what prefix caching leans on): the step
    WRITES exactly one position — ``pos``, into block
    ``block_tables[b, pos // bs]`` — and only READS every earlier
    position through the table.  The engine may therefore start a
    sequence at any ``pos > 0`` whose history blocks already hold valid
    K/V (shared prefix-cache blocks seeded into the table); those shared
    blocks are read-only by construction because every write lands at
    ``pos >= cached_tokens``, i.e. in a private tail block.  The values a
    cache hit skips recomputing are bitwise the ones this step would
    have produced, so output parity is structural, not numerical."""
    bs = kv_config.block_size
    int8 = kv_config.dtype == "int8"

    def step(kv_carry, params, tok, pos, block_tables, context_lens):
        tok = tok.astype(jnp.int32)
        pos = pos.astype(jnp.int32)
        block_tables = block_tables.astype(jnp.int32)
        context_lens = context_lens.astype(jnp.int32)
        blk_ids = jnp.take_along_axis(
            jnp.maximum(block_tables, 0), (pos // bs)[:, None], axis=1)[:, 0]
        offs = pos % bs
        if int8:
            k_c, v_c, k_s, v_s = kv_carry
        else:
            k_c, v_c = kv_carry

        def attend(l, q, k, v):
            nonlocal k_c, v_c
            if not int8:
                k_c = k_c.at[l, blk_ids, offs].set(k)
                v_c = v_c.at[l, blk_ids, offs].set(v)
                return paged_attention(q, k_c[l], v_c[l], block_tables,
                                       context_lens)
            nonlocal k_s, v_s
            qk, sk = _kv.quantize_kv(k)
            qv, sv = _kv.quantize_kv(v)
            k_c = k_c.at[l, blk_ids, offs].set(qk)
            v_c = v_c.at[l, blk_ids, offs].set(qv)
            k_s = k_s.at[l, blk_ids, offs].set(sk)
            v_s = v_s.at[l, blk_ids, offs].set(sv)
            idx = jnp.maximum(block_tables, 0)
            bb, maxb = block_tables.shape
            kk = _kv.dequantize_kv(jnp.take(k_c[l], idx, axis=0),
                                   jnp.take(k_s[l], idx, axis=0))
            vv = _kv.dequantize_kv(jnp.take(v_c[l], idx, axis=0),
                                   jnp.take(v_s[l], idx, axis=0))
            kk = kk.reshape(bb, maxb * bs, cfg.heads, cfg.head_dim)
            vv = vv.reshape(bb, maxb * bs, cfg.heads, cfg.head_dim)
            return masked_attention(q, kk, vv, context_lens)

        logits = _token_logits(params, cfg, tok, pos, attend)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        carry = (k_c, v_c, k_s, v_s) if int8 else (k_c, v_c)
        return carry, nxt, logits

    return step


# -- multi-token paged step (speculative verify / prefill chunks) ------------

def make_paged_step_multi(cfg, kv_config, width):
    """-> step(kv_carry, params, tok, pos, block_tables, context_lens)
    scoring ``width`` query tokens per lane in ONE call: tok/pos/
    context_lens are [B, width], block_tables stays [B, MAXB]; returns
    (new_kv_carry, next_tokens [B, width], logits [B, width, vocab]).

    The body is the single-token step composed ``width`` times inside
    one jit — each position runs the IDENTICAL write-then-attend op
    sequence at identical shapes, which is what keeps a speculative
    verify's argmax chain bitwise-equal to ``width`` non-speculative
    steps (the acceptance bar the spec parity tests assert).  Column j
    of pos/context_lens belongs to query j; lanes feeding fewer than
    ``width`` real tokens freeze their later columns' lens so the junk
    columns' (discarded) logits never read an unwritten position, and
    their writes land beyond every lens — overwritten before any later
    step can attend to them."""
    base = make_paged_step(cfg, kv_config)

    def step(kv_carry, params, tok, pos, block_tables, context_lens):
        tok = tok.astype(jnp.int32)
        pos = pos.astype(jnp.int32)
        context_lens = context_lens.astype(jnp.int32)
        nxts, logits = [], []
        for j in range(width):
            kv_carry, nxt, lg = base(kv_carry, params, tok[:, j],
                                     pos[:, j], block_tables,
                                     context_lens[:, j])
            nxts.append(nxt)
            logits.append(lg)
        return kv_carry, jnp.stack(nxts, axis=1), jnp.stack(logits, axis=1)

    return step


# -- draft rollout (speculative proposals) -----------------------------------

def make_draft_rollout(cfg, kv_config, k):
    """-> step(kv_carry, params, tok, pos, block_tables, context_lens,
    max_pos) proposing ``k`` tokens per lane in ONE call: feed tok[b] at
    pos[b], take the argmax, feed it at pos[b]+1, ... — the draft's
    greedy chain, writing its K/V through the draft's own paged lanes as
    it goes.  tok/pos/context_lens/max_pos are [B] (context_lens 0 marks
    an idle lane, whose writes land in the scratch block and whose lens
    stays frozen at 0).  ``max_pos`` clamps the chain's write position:
    a lane whose sequence budget ends before p+k-1 keeps re-writing its
    final reserved position instead of touching blocks it never
    reserved — those clamped writes sit beyond the accepted
    context_lens, so they are re-written before anything attends them.
    Returns (new_kv_carry, proposals [B, k])."""
    base = make_paged_step(cfg, kv_config)

    def step(kv_carry, params, tok, pos, block_tables, context_lens,
             max_pos):
        tok = tok.astype(jnp.int32)
        pos = pos.astype(jnp.int32)
        context_lens = context_lens.astype(jnp.int32)
        max_pos = max_pos.astype(jnp.int32)
        live = context_lens > 0
        props = []
        for j in range(k):
            kv_carry, nxt, _lg = base(
                kv_carry, params, tok,
                jnp.minimum(pos + j, max_pos), block_tables,
                jnp.where(live,
                          jnp.minimum(context_lens + j, max_pos + 1), 0))
            props.append(nxt)
            tok = nxt
        return kv_carry, jnp.stack(props, axis=1)

    return step


# -- unpaged reference -------------------------------------------------------

def make_unpaged_step(cfg, pad_len):
    """Reference step over contiguous per-lane K/V [L, B, pad_len, H, D].
    Same ``masked_attention`` core at the same [B, pad_len, H, D] shapes
    as the paged gather path — the bitwise comparison target."""

    def step(kv_carry, params, tok, pos, context_lens):
        tok = tok.astype(jnp.int32)
        pos = pos.astype(jnp.int32)
        context_lens = context_lens.astype(jnp.int32)
        k_c, v_c = kv_carry
        lanes = jnp.arange(k_c.shape[1], dtype=jnp.int32)

        def attend(l, q, k, v):
            nonlocal k_c, v_c
            k_c = k_c.at[l, lanes, pos].set(k)
            v_c = v_c.at[l, lanes, pos].set(v)
            return masked_attention(q, k_c[l], v_c[l], context_lens)

        logits = _token_logits(params, cfg, tok, pos, attend)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (k_c, v_c), nxt, logits

    return step


def unpaged_generate(cfg, params, prompt_ids, max_new, pad_len=None,
                     eos_id=-1, return_logits=False):
    """Greedy single-sequence reference loop (no paging, no batching):
    feed the prompt one token per step, then decode ``max_new`` tokens.
    ``pad_len`` must match the paged path's gathered history length
    (MAXB * block_size) for the bitwise comparison."""
    if pad_len is None:
        pad_len = cfg.max_seq
    step = jax.jit(make_unpaged_step(cfg, pad_len), donate_argnums=(0,))
    jparams = {k: jnp.asarray(v) for k, v in params.items()}
    kv = (jnp.zeros((cfg.layers, 1, pad_len, cfg.heads, cfg.head_dim),
                    jnp.float32),
          jnp.zeros((cfg.layers, 1, pad_len, cfg.heads, cfg.head_dim),
                    jnp.float32))
    prompt_ids = [int(t) for t in prompt_ids]
    out, logits_hist = [], []
    tok = prompt_ids[0]
    pos = 0
    while len(out) < max_new:
        kv, nxt, logits = step(
            kv, jparams, jnp.asarray([tok], jnp.int32),
            jnp.asarray([pos], jnp.int32),
            jnp.asarray([pos + 1], jnp.int32))
        pos += 1
        if pos < len(prompt_ids):
            tok = prompt_ids[pos]          # still feeding the prompt
            continue
        tok = int(nxt[0])
        out.append(tok)
        if return_logits:
            logits_hist.append(np.asarray(logits[0]))
        if tok == eos_id:
            break
    return (out, logits_hist) if return_logits else out
