"""Serving client: request/reply over the tensor-RPC wire + fleet
failover.

One ``infer`` is a send (``__infer__:<req_id>``) followed by a
deadline-bounded blocking GET on ``__reply__:<req_id>`` — the transport
parks the GET server-side until the dispatcher publishes the reply, so
there is no polling loop.  Inference is pure (no server-side state
mutation beyond counters), so on a dead/hung replica the request is
simply REPLAYED against the next live endpoint; the endpoints file the
fleet coordinator maintains (FLAGS_serving_endpoints_file) is re-read on
every failure so a shrunk fleet stops receiving traffic for dead
replicas.  A request is "dropped" only when every endpoint attempt fails
— the loadgen asserts that count is zero through a SIGKILL.

Replays trigger on ConnectionError AND on a server-side "timeout" reply
(a replica that answered "deadline expired in queue" is overloaded, not
authoritative — another replica may still make the SLO).  "shed" replies
are ALSO retried (up to ``FLAGS_serving_client_shed_retries`` extra
attempts) after honoring the server's ``retry_after_ms`` hint with
exponential backoff + jitter, so callers stop hand-rolling shed loops;
``client_shed_retries_total`` counts them.  For the autoregressive path
(``generate``/``generate_stream``), the client sends
``__abort__:<req_id>`` to the endpoint it is abandoning before replaying
elsewhere, so a half-prefilled sequence can't pin paged KV blocks on a
replica that will never be asked for the answer.

Requests carry an optional SLO ``tier`` ("paid"/"free"/"batch") in the
meta; the engine's deadline-weighted admission sheds low tiers first
under overload.

Disaggregated fleets publish a ``roles`` column beside the endpoints
(serving/fleet.py): ``generate`` then lands ``__generate__`` on a
prefill-role replica, reads its ``__pair__:<req_id>`` routing hint, and
walks the ``__stream__``/``__reply__`` vars on the named decode-role
replica (or on the same connection when the hint is None — no live
decode peer).  On failover the abort goes to BOTH halves, decode first,
so a dead pair can't strand adopted KV blocks on the survivor.

Session migration (serving/migrate.py) adds two recovery upgrades over
blind replay, both transparent to callers:

- **follow**: a replica that migrated the session away finishes it with
  status "migrated" (terminal stream chunk + reply phases carrying
  ``migrated_to``); the client hops to that endpoint and keeps walking
  the SAME stream indices — the destination resumes emission exactly
  where the source stopped, so no index is ever skipped or re-yielded.
- **resume**: on ConnectionError mid-stream the client re-submits
  ``__resume__:<req_id>`` with the tokens it already holds instead of
  replaying from scratch; a warm survivor (prior traffic or migration)
  skips straight past the sealed history.  A refused resume falls back
  to the ordinary fresh-req_id replay.

Either way ``generate`` dedupes delivered chunks by token index (greedy
decode is deterministic, so a replayed prefix is bitwise identical):
``on_token`` and ``generate_stream`` never see an index twice even when
a slow victim raced extra chunks out before dying.
"""

import json
import os
import random
import time
import uuid

import numpy as np

from ..core import telemetry as _tm
from ..core import tracing as _tr
from ..native.rpc import RpcClient
from . import codec
from .engine import InferReply

__all__ = ["ServingClient", "read_endpoints_file", "read_endpoints_doc"]


def _flag(name):
    from .. import flags

    return flags.flag(name)


def read_endpoints_file(path):
    """{"epoch": N, "endpoints": [...]} written by the fleet coordinator
    (atomic rename, so a partial read can't happen)."""
    with open(path) as f:
        doc = json.load(f)
    return [str(e) for e in doc.get("endpoints", [])]


def read_endpoints_doc(path):
    """Endpoints plus the optional disaggregation role column: returns
    (endpoints, roles-or-None).  A roles list that doesn't parallel the
    endpoints (torn hand-edit) is dropped rather than misrouting."""
    with open(path) as f:
        doc = json.load(f)
    eps = [str(e) for e in doc.get("endpoints", [])]
    roles = doc.get("roles")
    if roles and len(roles) == len(eps):
        return eps, [str(r) for r in roles]
    return eps, None


class ServingClient:
    def __init__(self, endpoints=None, endpoints_file=None,
                 tenant="default", deadline_ms=None, roles=None):
        self.endpoints_file = endpoints_file or \
            _flag("serving_endpoints_file") or None
        self._static = list(endpoints or [])
        # static role column parallel to ``endpoints`` (tests / no fleet
        # file); with a file the coordinator's published column wins
        self._roles = list(roles) if roles else None
        if self._roles and len(self._roles) != len(self._static):
            raise ValueError("client roles must parallel endpoints")
        self.tenant = tenant
        self.default_deadline_ms = float(
            deadline_ms if deadline_ms is not None
            else _flag("serving_deadline_ms"))
        self._rr = 0
        self.failovers = 0
        self.shed_retries = 0
        if not self._static and not self.endpoints_file:
            raise ValueError("ServingClient needs endpoints or an "
                             "endpoints file")

    def endpoints(self):
        if self.endpoints_file:
            try:
                eps = read_endpoints_file(self.endpoints_file)
                if eps:
                    return eps
            except (OSError, ValueError):
                pass
        return list(self._static)

    def endpoints_with_roles(self):
        """[(endpoint, role), ...] — role is "serve" when no column is
        published (monolith fleet, old endpoints file)."""
        if self.endpoints_file:
            try:
                eps, roles = read_endpoints_doc(self.endpoints_file)
                if eps:
                    return list(zip(eps, roles or ["serve"] * len(eps)))
            except (OSError, ValueError):
                pass
        return list(zip(self._static,
                        self._roles or ["serve"] * len(self._static)))

    # -- one-shot GET helpers ------------------------------------------------

    def _get_packed(self, endpoint, key, timeout):
        c = RpcClient(endpoint, connect_timeout=min(timeout, 5.0),
                      rpc_deadline=timeout, retry_times=0)
        try:
            return codec.unpack(c.get_var(key))
        finally:
            c.close()

    def spec(self, model, timeout=10.0):
        """Feed/fetch signature published by the server (__spec__ RPC)."""
        for ep in self.endpoints():
            try:
                meta, _ = self._get_packed(ep, codec.SPEC_KEY + model,
                                           timeout)
                return meta
            except ConnectionError:
                continue
        raise ConnectionError("no live endpoint answered __spec__:%s"
                              % model)

    # -- inference -----------------------------------------------------------

    def _shed_backoff(self, reply, sheds):
        """Honor the server's retry_after_ms hint: exponential backoff on
        repeat sheds, +-50% jitter so a shed herd doesn't re-arrive in
        lockstep."""
        base_s = min(max(reply.retry_after_ms, 1.0), 1000.0) / 1e3
        delay = min(base_s * (2.0 ** sheds), 2.0)
        time.sleep(delay * (0.5 + random.random()))
        self.shed_retries += 1
        _tm.inc("client_shed_retries_total")

    def infer(self, model, feeds, deadline_ms=None, max_attempts=None,
              tier=None):
        """Run one request; fails over across live endpoints.  Returns an
        InferReply whose status is ok|shed|timeout|error, or "dropped"
        when every endpoint attempt failed."""
        deadline_ms = float(deadline_ms or self.default_deadline_ms)
        req_id = uuid.uuid4().hex
        # root span of the cross-process trace; its context rides the
        # request meta so the server parents its admission span under it
        root = _tr.start_span("client.infer", model=model,
                              tenant=self.tenant, req_id=req_id)
        names = list(feeds)
        meta_req = {"model": model, "tenant": self.tenant,
                    "req_id": req_id, "deadline_ms": deadline_ms,
                    "feeds": names}
        if tier:
            meta_req[codec.TIER] = tier
        if root.traceparent:
            meta_req[codec.TRACEPARENT] = root.traceparent
        payload = codec.pack(meta_req, [feeds[n] for n in names])
        # reply wait: the request may sit a full deadline in the queue and
        # then still be served — bound the GET at deadline + slack
        get_timeout = deadline_ms / 1e3 + 30.0
        t0 = time.perf_counter()
        last_err = None
        last_reply = None
        sheds = 0
        shed_cap = int(_flag("serving_client_shed_retries") or 0)
        eps = self.endpoints()
        attempts = int(max_attempts or max(2 * len(eps), 2) + shed_cap)
        for i in range(attempts):
            if i:
                self.failovers += 1
                time.sleep(min(0.05 * i, 0.5))
                eps = self.endpoints()
            if not eps:
                last_err = "endpoints file empty"
                continue
            ep = eps[self._rr % len(eps)]
            self._rr += 1
            try:
                c = RpcClient(ep, connect_timeout=2.0,
                              rpc_deadline=get_timeout, retry_times=0)
                try:
                    # activate the root so the SEND frame gets stamped
                    # with its context (native/rpc.py stamp_wire_name)
                    with _tr.activate(root):
                        c.send_var(codec.INFER_KEY + req_id, payload)
                        meta, arrays = codec.unpack(
                            c.get_var(codec.REPLY_KEY + req_id))
                finally:
                    c.close()
            except ConnectionError as e:
                last_err = str(e)
                continue
            reply = InferReply(
                meta.get("status", "error"),
                outputs=dict(zip(meta.get("outputs", []), arrays)),
                error=meta.get("error"),
                retry_after_ms=meta.get("retry_after_ms", 0.0),
                phases=dict(meta.get("phases") or {}))
            reply.latency_ms = (time.perf_counter() - t0) * 1e3
            # wire_ms: what the client saw minus what the server spent
            srv_ms = float(meta.get("latency_ms") or 0.0)
            if srv_ms > 0.0:
                reply.phases["wire_ms"] = round(
                    max(reply.latency_ms - srv_ms, 0.0), 3)
            if reply.status == "timeout" and i + 1 < attempts:
                # overloaded replica, not a verdict — replay elsewhere
                last_err = "server timeout: %s" % reply.error
                last_reply = reply
                continue
            if reply.status == "shed" and sheds < shed_cap \
                    and i + 1 < attempts:
                # the server told us when it expects capacity — wait it
                # out (with jitter) instead of failing the caller
                last_err = "shed: %s" % reply.error
                last_reply = reply
                self._shed_backoff(reply, sheds)
                sheds += 1
                # fresh req_id: the shed reply is already published under
                # the old one, and a same-endpoint retry must not read it
                req_id = uuid.uuid4().hex
                meta_req["req_id"] = req_id
                payload = codec.pack(meta_req, [feeds[n] for n in names])
                continue
            root.annotate(status=reply.status, endpoint=ep,
                          attempts=i + 1).end()
            return reply
        if last_reply is not None:
            root.annotate(status=last_reply.status,
                          attempts=attempts).end()
            return last_reply
        root.annotate(status="dropped", attempts=attempts).end()
        return InferReply(
            "dropped", error="all %d attempts failed: %s"
            % (attempts, last_err),
            latency_ms=(time.perf_counter() - t0) * 1e3)

    # -- autoregressive decode -----------------------------------------------

    def _abort(self, endpoint, req_id):
        """Best-effort abandonment notice before replaying elsewhere —
        frees the sequence's paged KV blocks on the old replica."""
        try:
            c = RpcClient(endpoint, connect_timeout=1.0, rpc_deadline=3.0,
                          retry_times=0)
            try:
                c.send_var(codec.ABORT_KEY + req_id,
                           codec.pack({"req_id": req_id}))
            finally:
                c.close()
        except Exception:
            pass

    def _abort_pair(self, endpoint, decode_ep, req_id):
        """Disaggregated abandonment: the decode half holds the adopted
        KV blocks, so it gets the abort FIRST; the prefill half follows
        (its __abort__ handler also relays a cancel, so either order
        alone would eventually converge — both sides free either way)."""
        if decode_ep and decode_ep != endpoint:
            self._abort(decode_ep, req_id)
        self._abort(endpoint, req_id)

    def _gen_candidates(self):
        """(endpoint, role) pairs eligible for __generate__: prefill-role
        replicas when the fleet publishes a role column (the pair var
        then routes the stream to a decode half), every non-decode
        endpoint otherwise (decode replicas only as a last resort — they
        can still serve monolith traffic)."""
        cand = self.endpoints_with_roles()
        pf = [(e, r) for e, r in cand if r == "prefill"]
        if pf:
            return pf
        return [(e, r) for e, r in cand if r != "decode"] or cand

    def generate(self, model, prompt_ids, max_new_tokens=16,
                 deadline_ms=None, eos_id=-1, stream=True, on_token=None,
                 max_attempts=None, tier=None):
        """One autoregressive request; returns an InferReply whose
        outputs["tokens"] holds the generated ids.  With ``stream`` the
        client walks per-token ``__stream__`` chunks, so the reply phases
        gain client-observed ``client_ttft_ms`` / ``client_itl_ms_samples``
        (wire-inclusive, what a user would feel); ``on_token(i, token)``
        fires per chunk.  Fails over across endpoints on ConnectionError
        and on server-side timeout replies, sending ``__abort__`` for the
        abandoned attempt first."""
        deadline_ms = float(deadline_ms or self.default_deadline_ms)
        req_id = uuid.uuid4().hex
        root = _tr.start_span("client.generate", model=model,
                              tenant=self.tenant, req_id=req_id)
        prompt = np.ascontiguousarray(
            np.asarray(prompt_ids, np.int32).reshape(-1))
        meta_req = {"model": model, "tenant": self.tenant,
                    "req_id": req_id, "deadline_ms": deadline_ms,
                    "max_new_tokens": int(max_new_tokens),
                    "eos_id": int(eos_id), "stream": bool(stream)}
        if tier:
            meta_req[codec.TIER] = tier
        if root.traceparent:
            meta_req[codec.TRACEPARENT] = root.traceparent
        payload = codec.pack(meta_req, [prompt])
        get_timeout = deadline_ms / 1e3 + 30.0
        t0 = time.perf_counter()
        last_err, last_reply = None, None
        sheds = 0
        shed_cap = int(_flag("serving_client_shed_retries") or 0)
        # tokens already DELIVERED to the caller, index == position:
        # survives failover so a replayed/resumed prefix (deterministic
        # greedy decode) is deduped instead of re-yielded
        received = []
        resume_allowed = bool(_flag("session_migration"))
        cand = self._gen_candidates()
        attempts = int(max_attempts or max(2 * len(cand), 2) + shed_cap)
        for i in range(attempts):
            if i:
                self.failovers += 1
                time.sleep(min(0.05 * i, 0.5))
                cand = self._gen_candidates()
            if not cand:
                last_err = "endpoints file empty"
                continue
            ep, ep_role = cand[self._rr % len(cand)]
            self._rr += 1
            resuming = bool(stream and received and resume_allowed and i)
            chunk_times = []
            decode_ep = None
            try:
                c = RpcClient(ep, connect_timeout=2.0,
                              rpc_deadline=get_timeout, retry_times=0)
                dc = None
                mc = None              # follow-the-migration connection
                try:
                    with _tr.activate(root):
                        reader = c
                        if resuming:
                            # crash-resume: same req_id, prompt + tokens
                            # we hold; the replica re-prefills only what
                            # its history index doesn't cover and emits
                            # from index len(received) onward
                            c.send_var(codec.RESUME_KEY + req_id,
                                       codec.pack(meta_req, [
                                           prompt, np.asarray(
                                               received, np.int32)]))
                            am, _ = codec.unpack(c.get_var(
                                codec.RESUME_ACK_KEY + req_id))
                            if am.get("status") != "resumed":
                                _tm.inc("client_resume_total",
                                        result="refused")
                                last_err = "resume refused: %s" \
                                    % am.get("error")
                                # fall back to the ordinary full replay
                                # under a fresh req_id for good
                                resume_allowed = False
                                req_id = uuid.uuid4().hex
                                meta_req["req_id"] = req_id
                                payload = codec.pack(meta_req, [prompt])
                                continue
                            _tm.inc("client_resume_total",
                                    result="resumed")
                        else:
                            c.send_var(codec.GEN_KEY + req_id, payload)
                            if ep_role == "prefill":
                                # pair routing hint (always published by
                                # a prefill replica): the stream and
                                # reply come from the decode half, or
                                # from this connection when the hint is
                                # None (no live decode peer — monolith
                                # fallback)
                                pm, _ = codec.unpack(c.get_var(
                                    codec.PAIR_KEY + req_id))
                                decode_ep = pm.get("decode")
                                if decode_ep:
                                    dc = RpcClient(decode_ep,
                                                   connect_timeout=2.0,
                                                   rpc_deadline=get_timeout,
                                                   retry_times=0)
                                    reader = dc
                        if stream:
                            # a resumed session's chunk keys start at
                            # len(received); a fresh/replayed one at 0
                            k = len(received) if resuming else 0
                            while True:
                                cm, _ = codec.unpack(reader.get_var(
                                    "%s%s:%d" % (codec.STREAM_KEY,
                                                 req_id, k)))
                                if cm.get("token") is not None:
                                    idx = int(cm["i"])
                                    chunk_times.append(
                                        time.perf_counter())
                                    if idx == len(received):
                                        received.append(int(cm["token"]))
                                        if on_token is not None:
                                            on_token(idx, int(cm["token"]))
                                    else:
                                        # replayed prefix chunk: already
                                        # delivered, never re-yield
                                        _tm.inc("client_stream_dup_total")
                                if cm.get("done"):
                                    if cm.get("status") == "migrated":
                                        # follow the session: the reply
                                        # names the destination, which
                                        # continues at this SAME index
                                        mm, _ = codec.unpack(
                                            reader.get_var(
                                                codec.REPLY_KEY + req_id))
                                        dest = (mm.get("phases") or {}
                                                ).get("migrated_to")
                                        if not dest:
                                            break
                                        if mc is not None:
                                            mc.close()
                                        mc = RpcClient(
                                            dest, connect_timeout=2.0,
                                            rpc_deadline=get_timeout,
                                            retry_times=0)
                                        reader = mc
                                        _tm.inc(
                                            "client_migrate_follow_total")
                                        continue
                                    break
                                k += 1
                        meta, arrays = codec.unpack(
                            reader.get_var(codec.REPLY_KEY + req_id))
                        while meta.get("status") == "migrated":
                            # non-stream follow: hop to the destination
                            # replica for the authoritative reply
                            dest = (meta.get("phases") or {}
                                    ).get("migrated_to")
                            if not dest:
                                break
                            if mc is not None:
                                mc.close()
                            mc = RpcClient(dest, connect_timeout=2.0,
                                           rpc_deadline=get_timeout,
                                           retry_times=0)
                            _tm.inc("client_migrate_follow_total")
                            meta, arrays = codec.unpack(
                                mc.get_var(codec.REPLY_KEY + req_id))
                finally:
                    c.close()
                    if dc is not None:
                        dc.close()
                    if mc is not None:
                        mc.close()
            except ConnectionError as e:
                last_err = str(e)
                # free the abandoned sequence on BOTH halves of a
                # disaggregated pair (the decode side holds the blocks);
                # with tokens in hand the next attempt RESUMES under the
                # SAME req_id (the abort is a no-op on a dead victim),
                # otherwise replay under a fresh req_id — the abort
                # publishes a terminal reply under the old one, which a
                # retry that lands on the same endpoint would read as
                # its own
                self._abort_pair(ep, decode_ep, req_id)
                if not (stream and received and resume_allowed):
                    req_id = uuid.uuid4().hex
                    meta_req["req_id"] = req_id
                    payload = codec.pack(meta_req, [prompt])
                continue
            reply = InferReply(
                meta.get("status", "error"),
                outputs=dict(zip(meta.get("outputs", []), arrays)),
                error=meta.get("error"),
                retry_after_ms=meta.get("retry_after_ms", 0.0),
                phases=dict(meta.get("phases") or {}))
            reply.latency_ms = (time.perf_counter() - t0) * 1e3
            srv_ms = float(meta.get("latency_ms") or 0.0)
            if srv_ms > 0.0:
                reply.phases["wire_ms"] = round(
                    max(reply.latency_ms - srv_ms, 0.0), 3)
            if chunk_times:
                reply.phases["client_ttft_ms"] = round(
                    (chunk_times[0] - t0) * 1e3, 3)
                reply.phases["client_itl_ms_samples"] = [
                    round((b - a) * 1e3, 3) for a, b in
                    zip(chunk_times, chunk_times[1:])]
            if reply.status == "timeout" and i + 1 < attempts:
                last_err = "server timeout: %s" % reply.error
                last_reply = reply
                self._abort_pair(ep, decode_ep, req_id)
                req_id = uuid.uuid4().hex
                meta_req["req_id"] = req_id
                payload = codec.pack(meta_req, [prompt])
                continue
            if reply.status == "shed" and sheds < shed_cap \
                    and i + 1 < attempts:
                # shed at admission: nothing to abort server-side, but a
                # same-endpoint retry needs a fresh req_id (the shed
                # reply is already published under the old one)
                last_err = "shed: %s" % reply.error
                last_reply = reply
                self._shed_backoff(reply, sheds)
                sheds += 1
                req_id = uuid.uuid4().hex
                meta_req["req_id"] = req_id
                payload = codec.pack(meta_req, [prompt])
                continue
            root.annotate(status=reply.status, endpoint=ep,
                          attempts=i + 1,
                          tokens=len(reply.outputs.get("tokens", ()))
                          ).end()
            return reply
        if last_reply is not None:
            root.annotate(status=last_reply.status,
                          attempts=attempts).end()
            return last_reply
        root.annotate(status="dropped", attempts=attempts).end()
        return InferReply(
            "dropped", error="all %d attempts failed: %s"
            % (attempts, last_err),
            latency_ms=(time.perf_counter() - t0) * 1e3)

    def generate_stream(self, model, prompt_ids, **kw):
        """Generator over (index, token) yielded as chunks arrive; the
        final InferReply is returned via StopIteration.value.  Indices
        are strictly sequential from 0 even across mid-stream failover,
        migration follow, and crash-resume — ``generate``'s index dedupe
        swallows any replayed prefix."""
        got = []
        kw["stream"] = True
        kw["on_token"] = lambda i, t: got.append((i, t))
        reply = self.generate(model, prompt_ids, **kw)
        for item in got:
            yield item
        return reply

    def alive(self, endpoint, timeout=3.0):
        """[rank, epoch, is_coordinator] or None (rpc.probe contract)."""
        from ..native import rpc as _rpc

        got = _rpc.probe(endpoint, key=codec.ALIVE_KEY, timeout=timeout)
        return None if got is None else [int(x) for x in got]

    def scrape(self, endpoint=None, timeout=10.0):
        """Live __metrics__ snapshot from one replica (default: first)."""
        from ..core import telemetry

        ep = endpoint or self.endpoints()[0]
        return telemetry.scrape(ep, timeout=timeout)

    # -- rollout admin -------------------------------------------------------

    def rollout(self, cmd, timeout=10.0):
        """Send one RolloutController command (start/flip/abort/status)
        to the coordinator; returns the reply meta.  Non-coordinator
        replicas answer "not coordinator" and are skipped."""
        last_err = None
        eps = self.endpoints()
        # try the coordinator first (alive() -> [rank, epoch, is_coord])
        eps = sorted(eps, key=lambda ep: 0 if (
            (self.alive(ep) or [0, 0, 0])[2]) else 1)
        for ep in eps:
            req_id = uuid.uuid4().hex
            try:
                c = RpcClient(ep, connect_timeout=2.0,
                              rpc_deadline=timeout, retry_times=0)
                try:
                    c.send_var(codec.ROLLOUT_CTL_KEY + req_id,
                               codec.pack(cmd))
                    meta, _ = codec.unpack(
                        c.get_var(codec.REPLY_KEY + req_id))
                finally:
                    c.close()
            except ConnectionError as e:
                last_err = str(e)
                continue
            if meta.get("status") == "error" and "coordinator" in (
                    meta.get("error") or ""):
                last_err = meta["error"]
                continue
            return meta
        raise ConnectionError("rollout command failed everywhere: %s"
                              % last_err)

    def rollout_state(self, endpoint, timeout=10.0):
        """One replica's applied version-routing doc (__rollout__ var):
        {"models": {base: {active, canary, fraction, state}}}."""
        meta, _ = self._get_packed(endpoint, codec.ROLLOUT_KEY, timeout)
        return meta
