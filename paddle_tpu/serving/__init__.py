"""Continuous-batching inference serving (ROADMAP "Production inference
serving").

Composes the earlier subsystems into a multi-tenant serving path:
``ServingEngine`` (admission queue + shape-bucketed continuous batching +
AOT bucket prewarm through Executor.warmup / FLAGS_compile_cache_dir),
``ServingServer``/``ServingClient`` (request-reply wire protocol over
native/rpc.py with ``__metrics__`` scraping), and ``ServingFleet``
(heartbeat/eviction membership reusing the elastic layer's liveness
machinery, with client failover via the endpoints file).

Autoregressive decode rides the same wire: ``DecodeEngine`` schedules
token-level continuous batches over an engine-owned ``PagedKVCache``
(serving/kv_cache.py), stepping the minimal decoder in
serving/decode_model.py through one AOT-compiled executable per lane
bucket; generated tokens stream back as ``__stream__`` chunks.

Disaggregated serving (PR 17) splits a fleet into prefill-role and
decode-role replicas: the prefill half runs admission + chunked prefill
and streams each sealed KV block to a decode peer as ``__kvxfer__``
frames (serving/disagg.py ``KVBlockSender``); the decode half adopts
them into its refcounted pool via the prefix-cache index
(``AdoptTracker`` + ``DecodeEngine.adopt_kv_block``) and serves the
stream/reply the client was routed to by the ``__pair__`` hint.

The control plane above the fleet (PR 16) rides the same pieces:
SLO-tiered deadline-weighted admission in the engines, an ``AutoScaler``
launching prewarmed standbys / draining idle replicas, and a
``RolloutController`` canarying ``name@v2`` behind a metrics gate with
automatic rollback (serving/rollout.py).

Live session migration (serving/migrate.py) makes in-flight generations
survive replica death, drain, and rollout without re-prefill: the engine
publishes each sequence's completed history blocks into the prefix index
under hash-chain digests, so a session is transferable as (manifest,
missing sealed blocks, one tail partial block) over the same
``__kvxfer__`` wire — ``SessionMigrator`` pushes on drain/pressure,
``ResumeBuffer`` + ``__resume__`` re-admit on the destination, and
greedy decode makes the continuation bitwise identical.

The fleet observability plane (PR 18, serving/fleetmon.py) scrapes
every live replica each tick, merges histograms exactly via the shared
telemetry bucket vectors, windows counter deltas into rates, evaluates
multi-window burn-rate SLO rules, and republishes the merged doc under
``__fleet__`` — the AutoScaler's pressure and the rollout gate's
verdicts read these fleet-wide values instead of one-replica instants.

Entry points: ``tools/serve.py``, ``tools/loadgen.py``, and
``tools/fleet_top.py``.
"""

from .client import ServingClient, read_endpoints_doc, \
    read_endpoints_file  # noqa: F401
from .disagg import AdoptTracker, KVBlockSender  # noqa: F401
from .engine import DecodeEngine, InferReply, ServingEngine, \
    parse_buckets, parse_tier_weights, tier_weight  # noqa: F401
from .fleet import AutoScaler, ServingFleet, \
    write_endpoints_file  # noqa: F401
from .fleetmon import FLEET_RPC_KEY, FleetMonitor, \
    parse_slo_rules  # noqa: F401
from .kv_cache import BlockAllocator, KVCacheConfig, PagedKVCache, \
    engine_owned_kv_bytes, plan_num_blocks  # noqa: F401
from .migrate import ResumeBuffer, SessionMigrator, \
    tail_digest  # noqa: F401
from .rollout import RolloutController, evaluate_gate  # noqa: F401
from .server import ServingServer  # noqa: F401

__all__ = [
    "ServingEngine", "DecodeEngine", "ServingServer", "ServingClient",
    "ServingFleet", "AutoScaler", "RolloutController", "evaluate_gate",
    "InferReply", "parse_buckets", "parse_tier_weights", "tier_weight",
    "read_endpoints_file", "read_endpoints_doc", "write_endpoints_file",
    "KVCacheConfig", "BlockAllocator", "PagedKVCache", "plan_num_blocks",
    "engine_owned_kv_bytes", "KVBlockSender", "AdoptTracker",
    "FleetMonitor", "parse_slo_rules", "FLEET_RPC_KEY",
    "SessionMigrator", "ResumeBuffer", "tail_digest",
]
