"""Continuous-batching inference serving (ROADMAP "Production inference
serving").

Composes the earlier subsystems into a multi-tenant serving path:
``ServingEngine`` (admission queue + shape-bucketed continuous batching +
AOT bucket prewarm through Executor.warmup / FLAGS_compile_cache_dir),
``ServingServer``/``ServingClient`` (request-reply wire protocol over
native/rpc.py with ``__metrics__`` scraping), and ``ServingFleet``
(heartbeat/eviction membership reusing the elastic layer's liveness
machinery, with client failover via the endpoints file).

Entry points: ``tools/serve.py`` and ``tools/loadgen.py``.
"""

from .client import ServingClient, read_endpoints_file  # noqa: F401
from .engine import InferReply, ServingEngine, parse_buckets  # noqa: F401
from .fleet import ServingFleet, write_endpoints_file  # noqa: F401
from .server import ServingServer  # noqa: F401

__all__ = [
    "ServingEngine", "ServingServer", "ServingClient", "ServingFleet",
    "InferReply", "parse_buckets", "read_endpoints_file",
    "write_endpoints_file",
]
