"""Paged KV-cache for autoregressive decode serving.

The decode engine owns a pool of fixed-size KV blocks (``[layers,
num_blocks, block_size, heads, head_dim]`` device arrays) and hands each
admitted sequence a *block table* — the list of physical blocks holding
its history, grown one block per ``block_size`` generated tokens.  The
physical layout is the point: sequences of wildly different lengths all
present the decode step with the same static shapes (token ids, tables
padded to ``max_seq // block_size`` slots, context lengths), so ONE
AOT-compiled step per lane bucket serves every mixture of lengths with
zero runtime XLA compiles, and a finished sequence returns its blocks to
the free list the same step it finishes.

``BlockAllocator`` is the host-side free list (LIFO for reuse locality;
all-or-nothing ``alloc`` so a half-admitted sequence never holds blocks).
It is *refcounted*: ``incref`` lets sequences share a block (prefix
caching), ``free`` decrements, and a ``seal``-ed block whose refcount
hits zero parks in an LRU *evictable* pool instead of the free list —
its content stays valid and revivable until ``alloc`` reclaims it under
pressure, so cache residency costs nothing when blocks are needed.
``PagedKVCache`` owns the device arrays as a donated carry: every decode
step consumes the current arrays and returns the updated ones
(``carry()``/``replace_carry()``), so the cache is updated in place on
device instead of being copied per token.

``PrefixCache`` is the content-addressed index over sealed blocks: a
per-model hash chain ``h_i = sha(h_{i-1}, block_token_ids)`` over *full*
prompt blocks keys each physical block, ``match`` revives the longest
cached prefix of a new prompt (capped at ``len(prompt) - 1`` tokens so
prefill always computes at least one tail token and never writes into a
shared block), and ``publish`` is first-publisher-wins.

Residency dtype (FLAGS_kv_cache_dtype): ``f32`` keeps bitwise parity
with the unpaged reference loop; ``int8`` stores quantized blocks plus
per-(block, position, head) max-abs scales — the EQuARX
quantize-for-the-wire idiom (PAPERS.md arXiv 2506.17615) applied to
residency, ~4x the tokens per HBM byte.

Sizing is budget-gated (the MEM001/MEM003 satellite):
``plan_num_blocks`` fits the pool under ``FLAGS_hbm_budget_bytes`` after
the model's resident bytes, and every live cache registers its footprint
so ``core/world_analysis.check_memory`` counts engine-owned KV blocks in
the static per-replica peak estimate.
"""

import hashlib
import threading
import weakref
from collections import OrderedDict

import jax.numpy as jnp

from ..core import telemetry as _tm

__all__ = ["KVCacheConfig", "BlockAllocator", "PagedKVCache",
           "PrefixCache",
           "plan_num_blocks", "block_bytes", "engine_owned_kv_bytes",
           "engine_owned_resident_bytes", "register_resident_bytes",
           "quantize_kv", "dequantize_kv"]

# default pool size when neither FLAGS_kv_cache_blocks nor an HBM budget
# pins one (CPU-tier tests and demos)
_DEFAULT_BLOCKS = 64

# Machine-readable concurrency contracts (tools/threadlint.py enforces
# these; core/concurrency_analysis.py merges every module's registry).
# Index -> allocator: PrefixCache methods call into BlockAllocator while
# holding the index lock (match -> incref, publish -> seal), never the
# reverse — the allocator reaches the index only through on_evict, which
# fires AFTER the allocator lock is released.
LOCK_ORDER = (
    ("PrefixCache._lock", "BlockAllocator._lock"),
)

# Callbacks whose registration contract is "invoked with no owner lock
# held" — CC105 flags any invocation site that still holds one.
UNLOCKED_CALLBACKS = (
    "BlockAllocator.on_evict",
)


class KVCacheConfig:
    """Static cache geometry; hidden = heads * head_dim per layer."""

    __slots__ = ("layers", "heads", "head_dim", "block_size", "num_blocks",
                 "dtype")

    def __init__(self, layers, heads, head_dim, block_size, num_blocks,
                 dtype="f32"):
        if dtype not in ("f32", "int8"):
            raise ValueError("kv_cache dtype must be f32|int8: %r" % dtype)
        if block_size <= 0 or num_blocks <= 1:
            raise ValueError("need block_size > 0 and num_blocks > 1 "
                             "(block 0 is the idle-lane scratch)")
        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.dtype = dtype


def block_bytes(config):
    """HBM bytes ONE block costs across all layers (K + V, + scales for
    int8)."""
    per_tok = config.heads * config.head_dim
    if config.dtype == "int8":
        tok = per_tok * 1 + config.heads * 4        # int8 payload + scales
    else:
        tok = per_tok * 4
    return 2 * config.layers * config.block_size * tok


def plan_num_blocks(config, model_resident_bytes=0, requested=None,
                    budget=None):
    """Budget-gated pool sizing -> (num_blocks, capped).

    ``requested`` (default FLAGS_kv_cache_blocks; <=0 = auto) asks for a
    pool size; ``budget`` (default FLAGS_hbm_budget_bytes; 0 = no gate)
    caps it at what fits beside the model's resident bytes.  A budget too
    small for even a 2-block pool raises — the engine must not start with
    a cache it cannot hold (FLAGS_hbm_budget_bytes gates cache sizing,
    not just the model)."""
    from .. import flags as _flags

    if requested is None:
        requested = int(_flags.flag("kv_cache_blocks") or 0)
    if budget is None:
        budget = int(_flags.flag("hbm_budget_bytes") or 0)
    per = block_bytes(config)
    if budget > 0:
        fit = int((budget - int(model_resident_bytes)) // per)
        if fit < 2:
            raise ValueError(
                "FLAGS_hbm_budget_bytes=%d leaves room for %d KV block(s) "
                "of %d bytes beside %d model-resident bytes; the decode "
                "cache needs >= 2 (shrink the model, raise the budget, or "
                "set FLAGS_kv_cache_dtype=int8)"
                % (budget, max(fit, 0), per, model_resident_bytes))
        if requested > 0:
            return min(requested, fit), fit < requested
        return fit, False
    return (requested if requested > 0 else _DEFAULT_BLOCKS), False


class BlockAllocator:
    """Refcounted host-side free list over physical block ids.

    ``reserve`` low ids never enter circulation (the cache reserves block
    0 as the idle-lane write scratch).  ``alloc`` is all-or-nothing: a
    request the pool cannot fully satisfy takes nothing (the engine
    sheds or preempts instead of deadlocking on a half-allocation).

    Sharing: ``alloc`` hands out blocks at refcount 1; ``incref`` takes
    another share (prefix-cache hits); ``free`` decrements and only a
    zero-ref block leaves circulation.  A ``seal``-ed block (content
    complete and content-addressed) parks in the LRU *evictable* pool at
    zero refs instead of the free list — still resident and revivable via
    ``incref``, but ``alloc`` reclaims evictable LRU-first once the free
    list runs dry (firing ``on_evict(block, tag)`` so the index forgets
    it).  ``reclaimable`` = free + evictable is what admission/shed
    decisions must budget against: a warm cache never causes a spurious
    shed."""

    def __init__(self, num_blocks, reserve=0):
        if num_blocks <= reserve:
            raise ValueError("num_blocks %d <= reserve %d"
                             % (num_blocks, reserve))
        self.num_blocks = int(num_blocks)
        self.reserve = int(reserve)
        # LIFO: the most recently freed block is the next handed out, so a
        # churning batch keeps touching the same hot cache lines
        self._free = list(range(num_blocks - 1, reserve - 1, -1))
        self._owned = set()             # ids with refcount >= 1
        self._ref = {}                  # id -> refcount (keys == _owned)
        self._sealed = {}               # id -> content tag (in-use, sealed)
        self._evictable = OrderedDict()  # id -> tag; zero-ref, LRU order
        self.on_evict = None            # fn(block, tag) after a reclaim
        self._lock = threading.Lock()
        self.high_water = 0

    @property
    def capacity(self):
        return self.num_blocks - self.reserve

    @property
    def num_free(self):
        with self._lock:
            return len(self._free)

    @property
    def num_evictable(self):
        with self._lock:
            return len(self._evictable)

    @property
    def reclaimable(self):
        """Blocks an ``alloc`` could obtain right now: free list plus the
        zero-ref evictable pool (cached content it may reclaim)."""
        with self._lock:
            return len(self._free) + len(self._evictable)

    @property
    def in_use(self):
        with self._lock:
            return len(self._owned)

    def refcount(self, block):
        with self._lock:
            return self._ref.get(block, 0)

    def alloc(self, n):
        """n blocks or None (OOM — nothing is taken).  Prefers the free
        list; reclaims evictable cached blocks LRU-first only when the
        free list runs dry (cache residency is free until pressure)."""
        if n <= 0:
            return []
        evicted = []
        with self._lock:
            if n > len(self._free) + len(self._evictable):
                _tm.inc("kv_block_oom_total")
                return None
            got = []
            while len(got) < n and self._free:
                got.append(self._free.pop())
            while len(got) < n:
                b, tag = self._evictable.popitem(last=False)   # LRU victim
                evicted.append((b, tag))
                got.append(b)
            for b in got:
                self._owned.add(b)
                self._ref[b] = 1
            self._note_high_water_locked()
            _tm.inc("kv_block_alloc_total", n)
            _tm.set_gauge("kv_blocks_in_use", len(self._owned))
            _tm.set_gauge("kv_blocks_evictable", len(self._evictable))
            cb = self.on_evict
        # the index callback runs outside the allocator lock (it takes the
        # PrefixCache lock; the module-level LOCK_ORDER registry declares
        # the index -> allocator order and UNLOCKED_CALLBACKS declares
        # this fired-unlocked contract — threadlint CC101/CC105 enforce it)
        for b, tag in evicted:
            if cb is not None:
                cb(b, tag)
        return got

    def incref(self, block):
        """Take another share of ``block``.  True if it was in use
        (refcount bumped) or parked evictable (revived at refcount 1);
        False if it has already been reclaimed — the caller's index entry
        is stale."""
        with self._lock:
            if block in self._owned:
                self._ref[block] += 1
                return True
            tag = self._evictable.pop(block, None)
            if tag is None:
                return False
            self._owned.add(block)
            self._ref[block] = 1
            self._sealed[block] = tag        # stays sealed: re-parks at 0
            self._note_high_water_locked()
            _tm.set_gauge("kv_blocks_in_use", len(self._owned))
            return True

    def seal(self, block, tag):
        """Mark an in-use block's content complete and content-addressed
        by ``tag``: at refcount zero it parks in the evictable pool
        (revivable) instead of returning to the free list."""
        with self._lock:
            if block not in self._owned:
                raise ValueError("seal of unallocated block %r" % (block,))
            self._sealed[block] = tag

    def free(self, blocks):
        """Drop one reference per block; a block released at refcount
        zero returns to the free list (or parks evictable when sealed).
        Double-free or a foreign id raises (an engine bug must be loud,
        not silent corruption)."""
        blocks = list(blocks)
        with self._lock:
            for b in blocks:
                if b not in self._owned:
                    raise ValueError("free of unallocated block %r" % (b,))
            released = 0
            for b in blocks:
                self._ref[b] -= 1
                if self._ref[b] > 0:
                    continue
                del self._ref[b]
                self._owned.discard(b)
                released += 1
                tag = self._sealed.pop(b, None)
                if tag is not None:
                    self._evictable[b] = tag     # newest = last (LRU front)
                else:
                    self._free.append(b)
            _tm.inc("kv_block_free_total", released)
            _tm.set_gauge("kv_blocks_in_use", len(self._owned))
            _tm.set_gauge("kv_blocks_evictable", len(self._evictable))

    def discard_evictable(self, block):
        """Truly free a zero-ref evictable block (back to the free list,
        content dropped).  The disaggregated abort-reconciliation path:
        blocks a decode replica adopted for a request that died on the
        prefill half are parked evictable, and the cancel relay discards
        them instead of waiting for allocation pressure.  Returns False
        when the block is not currently evictable (already reclaimed, or
        revived by a matching sequence — in-use blocks are freed by their
        owner at finish)."""
        with self._lock:
            if block not in self._evictable:
                return False
            del self._evictable[block]
            self._free.append(block)
            _tm.inc("kv_block_discard_total")
            _tm.set_gauge("kv_blocks_evictable", len(self._evictable))
            return True

    def _note_high_water_locked(self):
        # evictable blocks still occupy physical pool slots
        occupied = len(self._owned) + len(self._evictable)
        self.high_water = max(self.high_water, occupied)

    def stats(self):
        with self._lock:
            return {"capacity": self.capacity, "free": len(self._free),
                    "in_use": len(self._owned),
                    "evictable": len(self._evictable),
                    "reclaimable": len(self._free) + len(self._evictable),
                    "high_water": self.high_water}


class PrefixCache:
    """Content-addressed index of sealed full-prompt KV blocks.

    Keyed by a per-model hash chain ``h_i = sha(h_{i-1},
    block_token_ids)`` over *full* prompt blocks, so a block's key commits
    to its entire prefix — equal keys mean bitwise-equal token history.
    ``match`` revives the longest indexed prefix of a prompt (taking one
    reference per shared block on the caller's behalf) capped at
    ``len(prompt) - 1`` tokens: prefill always computes at least one tail
    token and every KV *write* lands in a private tail block — shared
    blocks are read-only by construction.  ``publish`` seals a
    freshly-filled block into the index, first-publisher-wins; the
    allocator's ``on_evict`` callback un-indexes reclaimed blocks."""

    def __init__(self, allocator, block_size, namespace=""):
        self.allocator = allocator
        self.block_size = int(block_size)
        self.namespace = str(namespace)
        self._seed = hashlib.sha256(
            ("kvprefix:%s" % namespace).encode()).digest()
        self._index = {}                 # hex digest -> physical block id
        self._lock = threading.Lock()
        # per-model cumulative token counts behind the advertised
        # prefix_cache_hit_rate{model=} gauge (1s __metrics__ republish)
        self.lookup_tokens = 0
        self.hit_tokens = 0
        allocator.on_evict = self._on_evict

    def chain(self, token_ids):
        """Hash chain over the full blocks of ``token_ids`` -> list of hex
        digests, one per full block."""
        bs = self.block_size
        out = []
        h = self._seed
        for j in range(len(token_ids) // bs):
            d = hashlib.sha256(h)
            d.update(b"".join(int(t).to_bytes(8, "little", signed=True)
                              for t in token_ids[j * bs:(j + 1) * bs]))
            h = d.digest()
            out.append(h.hex())
        return out

    def extend_chain(self, prev_hex, block_tokens):
        """One hash-chain step past an existing digest: ``prev_hex`` is
        the previous block's hex digest (None for the chain seed) and
        ``block_tokens`` the next block's token ids -> next hex digest.
        Lets a decoding sequence extend its prompt chain over generated
        tokens incrementally (live session migration) without rehashing
        the whole history per block boundary."""
        h = self._seed if prev_hex is None else bytes.fromhex(prev_hex)
        d = hashlib.sha256(h)
        d.update(b"".join(int(t).to_bytes(8, "little", signed=True)
                          for t in block_tokens))
        return d.hexdigest()

    def match_digests(self, digests):
        """Longest indexed prefix of a precomputed digest chain ->
        ``blocks`` with one reference taken per block (the resume-path
        twin of ``match``: the caller already knows the full-history
        chain — prompt ++ emitted tokens — and, unlike prefill, needs no
        one-token cap because the next fed token is already decided)."""
        blocks = []
        with self._lock:
            for d in digests:
                b = self._index.get(d)
                if b is None:
                    break
                if not self.allocator.incref(b):
                    self._index.pop(d, None)
                    break
                blocks.append(b)
        return blocks

    def match(self, prompt_ids):
        """Longest cached prefix -> ``(blocks, cached_tokens, hashes)``.

        ``blocks`` arrive with one reference taken per block (the caller
        frees them like any owned block); ``hashes`` is the full-prompt
        chain, reused by the caller when publishing the tail."""
        hashes = self.chain(prompt_ids)
        max_blocks = max(0, (len(prompt_ids) - 1) // self.block_size)
        blocks = []
        with self._lock:
            for j in range(min(len(hashes), max_blocks)):
                b = self._index.get(hashes[j])
                if b is None:
                    break
                if not self.allocator.incref(b):
                    # reclaimed under us without the callback having run
                    # yet — forget the stale entry and stop matching
                    self._index.pop(hashes[j], None)
                    break
                blocks.append(b)
        cached = len(blocks) * self.block_size
        with self._lock:
            self.lookup_tokens += len(prompt_ids)
            self.hit_tokens += cached
        _tm.inc("prefix_cache_lookup_tokens_total", len(prompt_ids))
        if cached:
            _tm.inc("prefix_cache_hit_tokens_total", cached)
        # namespace-labeled twins of the counters above: the 1s republish
        # derives a WINDOWED per-namespace hit rate from their series
        # deltas (prefix_cache_ns_hit_rate{namespace=}) so prefix-aware
        # routers can bias on recent affinity, not lifetime averages
        _tm.inc("prefix_cache_ns_lookup_tokens_total", len(prompt_ids),
                namespace=self.namespace)
        if cached:
            _tm.inc("prefix_cache_ns_hit_tokens_total", cached,
                    namespace=self.namespace)
        return blocks, cached, hashes

    def hit_rate(self):
        """Cumulative per-model hit fraction (0.0 before any lookup)."""
        with self._lock:
            if self.lookup_tokens <= 0:
                return 0.0
            return self.hit_tokens / float(self.lookup_tokens)

    def lookup(self, digest):
        """Physical block currently indexed under ``digest``, or None.
        Takes no reference — a routing/dedupe peek, not an acquisition."""
        with self._lock:
            return self._index.get(digest)

    def publish(self, block, digest):
        """Index a freshly-filled full-prompt ``block`` under ``digest``.
        First-publisher-wins: a duplicate digest leaves the block private
        and returns False."""
        with self._lock:
            if digest in self._index:
                return False
            self.allocator.seal(block, digest)
            self._index[digest] = block
            _tm.inc("prefix_cache_blocks_published_total")
            return True

    def forget(self, digest):
        """Un-index ``digest`` and truly free its block when it sits
        zero-ref in the evictable pool (the adopted-block abort path).
        A block revived in-use by a live sequence only loses its index
        entry — its owner frees it at finish.  Returns True when the
        entry existed."""
        with self._lock:
            b = self._index.pop(digest, None)
        if b is None:
            return False
        # outside our lock mirrors the on_evict ordering (index ->
        # allocator); discard_evictable is a no-op for in-use blocks
        self.allocator.discard_evictable(b)
        return True

    def _on_evict(self, block, tag):
        with self._lock:
            if self._index.get(tag) == block:
                del self._index[tag]
        _tm.inc("prefix_cache_evictions_total")

    def __len__(self):
        with self._lock:
            return len(self._index)


# live caches, summed into the MEM001 static peak estimate
_LIVE = weakref.WeakSet()

# engine-owned resident weights (target + draft decoder params), keyed by
# the owning object so the registration dies with its model entry
_LIVE_RESIDENT = weakref.WeakKeyDictionary()


def engine_owned_kv_bytes():
    """Total HBM bytes of every live PagedKVCache in this process —
    world_analysis.check_memory folds this into MEM001/MEM003."""
    return sum(c.nbytes for c in list(_LIVE))


def register_resident_bytes(owner, nbytes):
    """Register `nbytes` of engine-owned resident weights (e.g. a decode
    model's target + draft params) against `owner` — the registration is
    weak, so it disappears with the owning model entry.  Folded into
    MEM001 beside the KV pool bytes."""
    _LIVE_RESIDENT[owner] = int(nbytes)


def engine_owned_resident_bytes():
    """Total engine-owned resident weight bytes (decoder params, incl.
    the speculative draft's) across live registrations."""
    return sum(_LIVE_RESIDENT.values())


def quantize_kv(x):
    """f32 [..., H, D] -> (int8 payload, f32 per-[..., H] max-abs scale).
    Symmetric round-to-nearest into [-127, 127]."""
    scale = jnp.max(jnp.abs(x), axis=-1) / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(x / safe[..., None]), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_kv(q, scale):
    return q.astype(jnp.float32) * scale[..., None]


class PagedKVCache:
    """Engine-owned paged K/V device arrays, carried (donated) through
    the decode step.  Block 0 is reserved: idle lanes in a partially-full
    bucket point their table at it, so their (masked, discarded) writes
    never touch a sequence's history."""

    def __init__(self, config):
        self.config = config
        self.allocator = BlockAllocator(config.num_blocks, reserve=1)
        shape = (config.layers, config.num_blocks, config.block_size,
                 config.heads, config.head_dim)
        if config.dtype == "int8":
            self._carry = (jnp.zeros(shape, jnp.int8),
                           jnp.zeros(shape, jnp.int8),
                           jnp.zeros(shape[:-1], jnp.float32),
                           jnp.zeros(shape[:-1], jnp.float32))
        else:
            self._carry = (jnp.zeros(shape, jnp.float32),
                           jnp.zeros(shape, jnp.float32))
        _LIVE.add(self)
        _tm.set_gauge("kv_cache_bytes", self.nbytes)

    @property
    def nbytes(self):
        return block_bytes(self.config) * self.config.num_blocks

    def carry(self):
        """The current device arrays, in decode-step argument order."""
        return self._carry

    def replace_carry(self, new_carry):
        """Install the step's returned (donated) arrays."""
        if len(new_carry) != len(self._carry):
            raise ValueError("carry arity changed")
        self._carry = tuple(new_carry)

    def blocks_for_tokens(self, n_tokens):
        """How many blocks a sequence of n_tokens needs."""
        bs = self.config.block_size
        return max(1, -(-int(n_tokens) // bs))

    # -- sealed-block export/import (the disaggregated transfer unit) --------

    def export_block(self, block):
        """Host copies of one physical block's slices of every carry
        array, in carry order: ``[k, v]`` for f32 residency, ``[k, v,
        k_scales, v_scales]`` for int8.  The wire payload IS the
        residency payload — prefill's compiled step is deterministic, so
        an adopted block is bitwise-identical to the one the decode
        replica would have computed itself."""
        import numpy as np

        return [np.asarray(c[:, block]) for c in self._carry]

    def import_block(self, block, arrays):
        """Install transferred payloads into physical ``block``.  The
        caller must hold the engine step lock (the carry is swapped
        wholesale) and own the block at refcount 1.  Shape/dtype mismatch
        raises — adopting a frame cut for different cache geometry would
        corrupt every sequence that later matches the digest."""
        import numpy as np

        if len(arrays) != len(self._carry):
            raise ValueError(
                "kv import arity mismatch: %d arrays for a %s-dtype "
                "carry of %d" % (len(arrays), self.config.dtype,
                                 len(self._carry)))
        new = []
        for c, a in zip(self._carry, arrays):
            a = np.asarray(a)
            want_shape = tuple(c.shape[:1] + c.shape[2:])
            if tuple(a.shape) != want_shape or a.dtype != c.dtype:
                raise ValueError(
                    "kv import geometry mismatch: got %s%s, carry wants "
                    "%s%s (block_size/heads/head_dim/dtype must agree "
                    "across the disaggregated pair)"
                    % (a.dtype, tuple(a.shape), c.dtype, want_shape))
            new.append(c.at[:, block].set(jnp.asarray(a)))
        self._carry = tuple(new)

    # -- multi-token growth / rollback (the speculative-decode contract) -----

    def ensure_table(self, table, blocks, upto_tokens):
        """Grow a sequence's block table to cover positions
        ``[0, upto_tokens)`` with ONE all-or-nothing allocation: either
        every missing slot is filled (True) or nothing is taken (False —
        the engine preempts or sheds).  This is the multi-token append
        API: a k-token speculative write (and a k-token prefill chunk)
        reserves all the blocks it may touch in one call instead of one
        alloc per token."""
        need = self.blocks_for_tokens(upto_tokens)
        have = len(blocks)
        if need <= have:
            return True
        got = self.allocator.alloc(need - have)
        if got is None:
            return False
        for i, b in enumerate(got):
            table[have + i] = b
        blocks.extend(got)
        return True

    def trim_table(self, table, blocks, upto_tokens):
        """Rollback: free every block beyond the one holding position
        ``upto_tokens - 1`` and clear its table slot.  With paged tables
        a rejected speculation costs no copies — the over-allocated
        blocks return to the free list and ``context_lens`` truncation
        masks the stale writes.  Returns the number of blocks freed."""
        keep = self.blocks_for_tokens(upto_tokens) if upto_tokens > 0 else 0
        if len(blocks) <= keep:
            return 0
        extra = blocks[keep:]
        del blocks[keep:]
        table[keep:keep + len(extra)] = -1
        self.allocator.free(extra)
        return len(extra)
