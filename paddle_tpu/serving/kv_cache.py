"""Paged KV-cache for autoregressive decode serving.

The decode engine owns a pool of fixed-size KV blocks (``[layers,
num_blocks, block_size, heads, head_dim]`` device arrays) and hands each
admitted sequence a *block table* — the list of physical blocks holding
its history, grown one block per ``block_size`` generated tokens.  The
physical layout is the point: sequences of wildly different lengths all
present the decode step with the same static shapes (token ids, tables
padded to ``max_seq // block_size`` slots, context lengths), so ONE
AOT-compiled step per lane bucket serves every mixture of lengths with
zero runtime XLA compiles, and a finished sequence returns its blocks to
the free list the same step it finishes.

``BlockAllocator`` is the host-side free list (LIFO for reuse locality;
all-or-nothing ``alloc`` so a half-admitted sequence never holds blocks).
``PagedKVCache`` owns the device arrays as a donated carry: every decode
step consumes the current arrays and returns the updated ones
(``carry()``/``replace_carry()``), so the cache is updated in place on
device instead of being copied per token.

Residency dtype (FLAGS_kv_cache_dtype): ``f32`` keeps bitwise parity
with the unpaged reference loop; ``int8`` stores quantized blocks plus
per-(block, position, head) max-abs scales — the EQuARX
quantize-for-the-wire idiom (PAPERS.md arXiv 2506.17615) applied to
residency, ~4x the tokens per HBM byte.

Sizing is budget-gated (the MEM001/MEM003 satellite):
``plan_num_blocks`` fits the pool under ``FLAGS_hbm_budget_bytes`` after
the model's resident bytes, and every live cache registers its footprint
so ``core/world_analysis.check_memory`` counts engine-owned KV blocks in
the static per-replica peak estimate.
"""

import threading
import weakref

import jax.numpy as jnp

from ..core import telemetry as _tm

__all__ = ["KVCacheConfig", "BlockAllocator", "PagedKVCache",
           "plan_num_blocks", "block_bytes", "engine_owned_kv_bytes",
           "engine_owned_resident_bytes", "register_resident_bytes",
           "quantize_kv", "dequantize_kv"]

# default pool size when neither FLAGS_kv_cache_blocks nor an HBM budget
# pins one (CPU-tier tests and demos)
_DEFAULT_BLOCKS = 64


class KVCacheConfig:
    """Static cache geometry; hidden = heads * head_dim per layer."""

    __slots__ = ("layers", "heads", "head_dim", "block_size", "num_blocks",
                 "dtype")

    def __init__(self, layers, heads, head_dim, block_size, num_blocks,
                 dtype="f32"):
        if dtype not in ("f32", "int8"):
            raise ValueError("kv_cache dtype must be f32|int8: %r" % dtype)
        if block_size <= 0 or num_blocks <= 1:
            raise ValueError("need block_size > 0 and num_blocks > 1 "
                             "(block 0 is the idle-lane scratch)")
        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.dtype = dtype


def block_bytes(config):
    """HBM bytes ONE block costs across all layers (K + V, + scales for
    int8)."""
    per_tok = config.heads * config.head_dim
    if config.dtype == "int8":
        tok = per_tok * 1 + config.heads * 4        # int8 payload + scales
    else:
        tok = per_tok * 4
    return 2 * config.layers * config.block_size * tok


def plan_num_blocks(config, model_resident_bytes=0, requested=None,
                    budget=None):
    """Budget-gated pool sizing -> (num_blocks, capped).

    ``requested`` (default FLAGS_kv_cache_blocks; <=0 = auto) asks for a
    pool size; ``budget`` (default FLAGS_hbm_budget_bytes; 0 = no gate)
    caps it at what fits beside the model's resident bytes.  A budget too
    small for even a 2-block pool raises — the engine must not start with
    a cache it cannot hold (FLAGS_hbm_budget_bytes gates cache sizing,
    not just the model)."""
    from .. import flags as _flags

    if requested is None:
        requested = int(_flags.flag("kv_cache_blocks") or 0)
    if budget is None:
        budget = int(_flags.flag("hbm_budget_bytes") or 0)
    per = block_bytes(config)
    if budget > 0:
        fit = int((budget - int(model_resident_bytes)) // per)
        if fit < 2:
            raise ValueError(
                "FLAGS_hbm_budget_bytes=%d leaves room for %d KV block(s) "
                "of %d bytes beside %d model-resident bytes; the decode "
                "cache needs >= 2 (shrink the model, raise the budget, or "
                "set FLAGS_kv_cache_dtype=int8)"
                % (budget, max(fit, 0), per, model_resident_bytes))
        if requested > 0:
            return min(requested, fit), fit < requested
        return fit, False
    return (requested if requested > 0 else _DEFAULT_BLOCKS), False


class BlockAllocator:
    """Host-side free list over physical block ids.

    ``reserve`` low ids never enter circulation (the cache reserves block
    0 as the idle-lane write scratch).  ``alloc`` is all-or-nothing: a
    request the free list cannot fully satisfy takes nothing (the engine
    sheds or preempts instead of deadlocking on a half-allocation)."""

    def __init__(self, num_blocks, reserve=0):
        if num_blocks <= reserve:
            raise ValueError("num_blocks %d <= reserve %d"
                             % (num_blocks, reserve))
        self.num_blocks = int(num_blocks)
        self.reserve = int(reserve)
        # LIFO: the most recently freed block is the next handed out, so a
        # churning batch keeps touching the same hot cache lines
        self._free = list(range(num_blocks - 1, reserve - 1, -1))
        self._owned = set()
        self._lock = threading.Lock()
        self.high_water = 0

    @property
    def capacity(self):
        return self.num_blocks - self.reserve

    @property
    def num_free(self):
        with self._lock:
            return len(self._free)

    @property
    def in_use(self):
        with self._lock:
            return len(self._owned)

    def alloc(self, n):
        """n blocks or None (OOM — nothing is taken)."""
        if n <= 0:
            return []
        with self._lock:
            if n > len(self._free):
                _tm.inc("kv_block_oom_total")
                return None
            got = [self._free.pop() for _ in range(n)]
            self._owned.update(got)
            self.high_water = max(self.high_water, len(self._owned))
            _tm.inc("kv_block_alloc_total", n)
            _tm.set_gauge("kv_blocks_in_use", len(self._owned))
        return got

    def free(self, blocks):
        """Return blocks to the free list; double-free or a foreign id
        raises (an engine bug must be loud, not silent corruption)."""
        blocks = list(blocks)
        with self._lock:
            for b in blocks:
                if b not in self._owned:
                    raise ValueError("free of unallocated block %r" % (b,))
            for b in blocks:
                self._owned.discard(b)
                self._free.append(b)
            _tm.inc("kv_block_free_total", len(blocks))
            _tm.set_gauge("kv_blocks_in_use", len(self._owned))

    def stats(self):
        with self._lock:
            return {"capacity": self.capacity, "free": len(self._free),
                    "in_use": len(self._owned),
                    "high_water": self.high_water}


# live caches, summed into the MEM001 static peak estimate
_LIVE = weakref.WeakSet()

# engine-owned resident weights (target + draft decoder params), keyed by
# the owning object so the registration dies with its model entry
_LIVE_RESIDENT = weakref.WeakKeyDictionary()


def engine_owned_kv_bytes():
    """Total HBM bytes of every live PagedKVCache in this process —
    world_analysis.check_memory folds this into MEM001/MEM003."""
    return sum(c.nbytes for c in list(_LIVE))


def register_resident_bytes(owner, nbytes):
    """Register `nbytes` of engine-owned resident weights (e.g. a decode
    model's target + draft params) against `owner` — the registration is
    weak, so it disappears with the owning model entry.  Folded into
    MEM001 beside the KV pool bytes."""
    _LIVE_RESIDENT[owner] = int(nbytes)


def engine_owned_resident_bytes():
    """Total engine-owned resident weight bytes (decoder params, incl.
    the speculative draft's) across live registrations."""
    return sum(_LIVE_RESIDENT.values())


def quantize_kv(x):
    """f32 [..., H, D] -> (int8 payload, f32 per-[..., H] max-abs scale).
    Symmetric round-to-nearest into [-127, 127]."""
    scale = jnp.max(jnp.abs(x), axis=-1) / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(x / safe[..., None]), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_kv(q, scale):
    return q.astype(jnp.float32) * scale[..., None]


class PagedKVCache:
    """Engine-owned paged K/V device arrays, carried (donated) through
    the decode step.  Block 0 is reserved: idle lanes in a partially-full
    bucket point their table at it, so their (masked, discarded) writes
    never touch a sequence's history."""

    def __init__(self, config):
        self.config = config
        self.allocator = BlockAllocator(config.num_blocks, reserve=1)
        shape = (config.layers, config.num_blocks, config.block_size,
                 config.heads, config.head_dim)
        if config.dtype == "int8":
            self._carry = (jnp.zeros(shape, jnp.int8),
                           jnp.zeros(shape, jnp.int8),
                           jnp.zeros(shape[:-1], jnp.float32),
                           jnp.zeros(shape[:-1], jnp.float32))
        else:
            self._carry = (jnp.zeros(shape, jnp.float32),
                           jnp.zeros(shape, jnp.float32))
        _LIVE.add(self)
        _tm.set_gauge("kv_cache_bytes", self.nbytes)

    @property
    def nbytes(self):
        return block_bytes(self.config) * self.config.num_blocks

    def carry(self):
        """The current device arrays, in decode-step argument order."""
        return self._carry

    def replace_carry(self, new_carry):
        """Install the step's returned (donated) arrays."""
        if len(new_carry) != len(self._carry):
            raise ValueError("carry arity changed")
        self._carry = tuple(new_carry)

    def blocks_for_tokens(self, n_tokens):
        """How many blocks a sequence of n_tokens needs."""
        bs = self.config.block_size
        return max(1, -(-int(n_tokens) // bs))

    # -- multi-token growth / rollback (the speculative-decode contract) -----

    def ensure_table(self, table, blocks, upto_tokens):
        """Grow a sequence's block table to cover positions
        ``[0, upto_tokens)`` with ONE all-or-nothing allocation: either
        every missing slot is filled (True) or nothing is taken (False —
        the engine preempts or sheds).  This is the multi-token append
        API: a k-token speculative write (and a k-token prefill chunk)
        reserves all the blocks it may touch in one call instead of one
        alloc per token."""
        need = self.blocks_for_tokens(upto_tokens)
        have = len(blocks)
        if need <= have:
            return True
        got = self.allocator.alloc(need - have)
        if got is None:
            return False
        for i, b in enumerate(got):
            table[have + i] = b
        blocks.extend(got)
        return True

    def trim_table(self, table, blocks, upto_tokens):
        """Rollback: free every block beyond the one holding position
        ``upto_tokens - 1`` and clear its table slot.  With paged tables
        a rejected speculation costs no copies — the over-allocated
        blocks return to the free list and ``context_lens`` truncation
        masks the stale writes.  Returns the number of blocks freed."""
        keep = self.blocks_for_tokens(upto_tokens) if upto_tokens > 0 else 0
        if len(blocks) <= keep:
            return 0
        extra = blocks[keep:]
        del blocks[keep:]
        table[keep:keep + len(extra)] = -1
        self.allocator.free(extra)
        return len(extra)
