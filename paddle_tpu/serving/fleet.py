"""Elastic serving replicas: fleet membership over the serving RPC port.

The elastic training layer (distributed/elastic.py) re-forms a collective
WORLD when a member dies; serving replicas are independent (no cross-
replica collectives), so the fleet layer only needs the membership half
of that machinery: the same ``HeartBeatMonitor`` liveness bookkeeping
(distributed/ps.py), the same ``__alive__`` probe contract
(``native.rpc.probe``), and the same publish-a-view flow — except the
view here is the **endpoints file** clients read to fail over
(FLAGS_serving_endpoints_file), plus a ``__fview__`` var for scraping.

Mechanics:

- every replica heartbeats the coordinator (lowest live rank) with
  ``__fhb__<rank>`` on the coordinator's SERVING port — heartbeats ride
  the same event stream as requests, so the coordinator's poll loop
  drives eviction checks with no extra socket;
- a SIGKILLed replica goes silent; after ``FLAGS_serving_hb_timeout`` the
  coordinator marks it dead and stages a shrunken view.  The view is
  PUBLISHED at a batch boundary (the engine's ``on_batch_boundary`` hook
  calls ``tick``) so a membership change never lands mid-batch — queued
  requests on the survivors are untouched, and the killed replica's
  in-flight clients replay against the new endpoints file;
- if the coordinator itself dies, the next-lowest live rank notices its
  heartbeats failing, probes every lower rank, and promotes itself
  (rewriting the endpoints file from a fresh probe of the member list).
"""

import json
import logging
import os
import threading
import time

import numpy as np

from ..core import telemetry as _tm
from ..distributed.ps import HeartBeatMonitor
from ..native import rpc as _rpc
from . import codec

__all__ = ["ServingFleet", "FLEET_HB", "FLEET_VIEW"]

FLEET_HB = "__fhb__"
FLEET_VIEW = "__fview__"
_PROMOTE_AFTER = 4  # consecutive heartbeat failures before probing


def _flag(name):
    from .. import flags

    return flags.flag(name)


def write_endpoints_file(path, epoch, endpoints):
    """Atomic (tmp + rename) so client reads never see a torn view."""
    doc = {"epoch": int(epoch), "endpoints": list(endpoints)}
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


class ServingFleet:
    def __init__(self, rank, endpoints, server, endpoints_file=None):
        self.rank = int(rank)
        self.endpoints = list(endpoints)
        self.server = server                     # ServingServer
        self.endpoints_file = endpoints_file or \
            _flag("serving_endpoints_file") or None
        self.epoch = 0
        self.live = set(range(len(self.endpoints)))
        self.mon = None                          # coordinator only
        self._coord_rank = min(self.live)
        self._hb_thread = None
        self._tick_thread = None
        self._stop = threading.Event()
        self._hb_failures = 0
        self._lock = threading.Lock()
        self._pending_view = False

    def is_coordinator(self):
        return self._coord_rank == self.rank

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self.server.attach_fleet(self)
        if self.is_coordinator():
            self._become_coordinator(initial=True)
        else:
            self.server.set_alive(self.epoch, False)
        self._start_heartbeat()
        return self

    def _become_coordinator(self, initial=False):
        timeout = float(_flag("serving_hb_timeout") or 2.0)
        if not initial:
            # promotion: rebuild liveness from a fresh probe of the list
            self.live = {r for r, ep in enumerate(self.endpoints)
                         if r == self.rank
                         or _rpc.probe(ep, key=codec.ALIVE_KEY,
                                       timeout=1.0) is not None}
            self.epoch += 1
            _tm.inc("serving_fleet_promotions_total")
            logging.warning("[serving-fleet] rank %d promoted to "
                            "coordinator (live=%s)", self.rank,
                            sorted(self.live))
        self._coord_rank = self.rank
        self.mon = HeartBeatMonitor(
            0, timeout_s=timeout, name="serving-fleet",
            worker_ids=sorted(self.live - {self.rank}))
        self.server.set_alive(self.epoch, True)
        self._publish_view()
        # heartbeats only wake the poll loop while peers are alive; a
        # self-tick keeps eviction checks running even with a silent fleet
        if self._tick_thread is None:
            self._tick_thread = threading.Thread(
                target=self._self_tick, name="fleet-tick", daemon=True)
            self._tick_thread.start()

    def _self_tick(self):
        interval = float(_flag("serving_hb_interval") or 0.3)
        me = self.endpoints[self.rank]
        while not self._stop.wait(interval):
            if not self.is_coordinator():
                continue
            try:
                c = _rpc.RpcClient(me, connect_timeout=1.0,
                                   rpc_deadline=2.0, retry_times=0)
                try:
                    c.send_var(FLEET_HB + str(self.rank),
                               np.asarray([self.rank], np.int64))
                finally:
                    c.close()
            except Exception:
                pass

    def _start_heartbeat(self):
        def loop():
            interval = float(_flag("serving_hb_interval") or 0.3)
            client = None
            while not self._stop.wait(interval):
                if self.is_coordinator():
                    continue
                try:
                    if client is None:
                        client = _rpc.RpcClient(
                            self.endpoints[self._coord_rank],
                            connect_timeout=1.0, rpc_deadline=2.0,
                            retry_times=0)
                    client.send_var(FLEET_HB + str(self.rank),
                                    np.asarray([self.rank], np.int64))
                    self._hb_failures = 0
                except Exception:
                    client = None
                    self._hb_failures += 1
                    if self._hb_failures >= _PROMOTE_AFTER:
                        self._hb_failures = 0
                        self._coordinator_lost()

        self._hb_thread = threading.Thread(target=loop, name="fleet-hb",
                                           daemon=True)
        self._hb_thread.start()

    def _coordinator_lost(self):
        """The coordinator stopped answering: lowest live rank takes over."""
        for r in sorted(self.live):
            if r == self.rank:
                break
            if r == self._coord_rank:
                continue
            if _rpc.probe(self.endpoints[r], key=codec.ALIVE_KEY,
                          timeout=1.0) is not None:
                self.live.discard(self._coord_rank)
                self._coord_rank = r
                return
        self.live.discard(self._coord_rank)
        self._become_coordinator()

    # -- event stream (called from the server poll loop) ---------------------

    def on_event(self, name, arr):
        if name.startswith(FLEET_HB) and self.mon is not None:
            r = int(arr[0])
            if r in self.live:
                self.mon.update(r)
            elif r != self.rank:
                # a relaunched/late replica re-announces itself
                self.live.add(r)
                self.mon.update(r)
                with self._lock:
                    self._pending_view = True

    def tick(self):
        """Eviction check + deferred view publication.  Runs on the poll
        loop after every event AND on the engine's batch-boundary hook, so
        a shrink always lands between batches."""
        if not self.is_coordinator() or self.mon is None:
            return
        dead = [r for r in self.mon.check() if r in self.live]
        if dead:
            for r in dead:
                self.live.discard(r)
                self.mon.remove(r)
            self.epoch += 1
            _tm.inc("serving_fleet_evictions_total", len(dead))
            _tm.event("serving_fleet_evict", dead=dead, epoch=self.epoch,
                      live=sorted(self.live))
            logging.warning("[serving-fleet] epoch %d: evicted %s, "
                            "live=%s", self.epoch, dead, sorted(self.live))
            with self._lock:
                self._pending_view = True
        publish = False
        with self._lock:
            if self._pending_view and not self.server.engine.in_batch:
                self._pending_view = False
                publish = True
        if publish:
            self._publish_view()

    def _publish_view(self):
        live_eps = [self.endpoints[r] for r in sorted(self.live)]
        self.server.rpc.set_var(
            FLEET_VIEW,
            np.asarray([self.epoch] + sorted(self.live), np.int64))
        if self.endpoints_file:
            try:
                write_endpoints_file(self.endpoints_file, self.epoch,
                                     live_eps)
            except OSError as e:
                logging.warning("[serving-fleet] endpoints file write "
                                "failed: %s", e)
        _tm.set_gauge("serving_fleet_size", len(self.live))
        _tm.set_gauge("serving_fleet_epoch", self.epoch)

    def view(self):
        return {"epoch": self.epoch, "live": sorted(self.live),
                "coordinator": self._coord_rank}

    def stop(self):
        self._stop.set()
