"""Elastic serving replicas: fleet membership over the serving RPC port.

The elastic training layer (distributed/elastic.py) re-forms a collective
WORLD when a member dies; serving replicas are independent (no cross-
replica collectives), so the fleet layer only needs the membership half
of that machinery: the same ``HeartBeatMonitor`` liveness bookkeeping
(distributed/ps.py), the same ``__alive__`` probe contract
(``native.rpc.probe``), and the same publish-a-view flow — except the
view here is the **endpoints file** clients read to fail over
(FLAGS_serving_endpoints_file), plus a ``__fview__`` var for scraping.

Mechanics:

- every replica heartbeats the coordinator (lowest live rank) with
  ``__fhb__<rank>`` on the coordinator's SERVING port — heartbeats ride
  the same event stream as requests, so the coordinator's poll loop
  drives eviction checks with no extra socket;
- a SIGKILLed replica goes silent; after ``FLAGS_serving_hb_timeout`` the
  coordinator marks it dead and stages a shrunken view.  The view is
  PUBLISHED at a batch boundary (the engine's ``on_batch_boundary`` hook
  calls ``tick``) so a membership change never lands mid-batch — queued
  requests on the survivors are untouched, and the killed replica's
  in-flight clients replay against the new endpoints file;
- if the coordinator itself dies, the next-lowest live rank notices its
  heartbeats failing, probes every lower rank, and promotes itself
  (rewriting the endpoints file from a fresh probe of the member list).
"""

import json
import logging
import os
import threading
import time

import numpy as np

from ..core import telemetry as _tm
from ..distributed.ps import HeartBeatMonitor
from ..native import rpc as _rpc
from . import codec

__all__ = ["ServingFleet", "AutoScaler", "FLEET_HB", "FLEET_VIEW"]

FLEET_HB = "__fhb__"
FLEET_VIEW = "__fview__"
_PROMOTE_AFTER = 4  # consecutive heartbeat failures before probing


def _flag(name):
    from .. import flags

    return flags.flag(name)


def write_endpoints_file(path, epoch, endpoints, rollout=None, roles=None):
    """Atomic (tmp + rename) so client reads never see a torn view.  The
    optional rollout doc rides along so a version flip is published in
    the SAME epoch bump as any membership change.  ``roles`` is the
    disaggregation column: a list parallel to ``endpoints`` of
    "serve" | "prefill" | "decode" — absent means every replica is a
    monolith (pre-disagg files stay readable, and old clients ignore
    the extra key)."""
    doc = {"epoch": int(epoch), "endpoints": list(endpoints)}
    if rollout:
        doc["rollout"] = rollout
    if roles:
        doc["roles"] = list(roles)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


class ServingFleet:
    def __init__(self, rank, endpoints, server, endpoints_file=None,
                 roles=None):
        self.rank = int(rank)
        self.endpoints = list(endpoints)
        # disaggregation role column, parallel to endpoints; None keeps
        # every rank a monolith ("serve") and the published files
        # byte-identical to the pre-disagg format
        if roles is not None and len(roles) != len(self.endpoints):
            raise ValueError("fleet roles column must parallel endpoints:"
                             " %d roles for %d endpoints"
                             % (len(roles), len(self.endpoints)))
        self.roles = list(roles) if roles is not None else None
        self.server = server                     # ServingServer
        self.endpoints_file = endpoints_file or \
            _flag("serving_endpoints_file") or None
        self.epoch = 0
        self.live = set(range(len(self.endpoints)))
        self.mon = None                          # coordinator only
        self._coord_rank = min(self.live)
        self._hb_thread = None
        self._tick_thread = None
        self._stop = threading.Event()
        self._hb_failures = 0
        self._lock = threading.Lock()
        self._pending_view = False
        self.rollout_doc = None         # published beside the endpoints
        self._retiring = set()          # ranks draining out (autoscaler)

    def is_coordinator(self):
        return self._coord_rank == self.rank

    def role_of(self, rank):
        if self.roles is None:
            return "serve"
        return self.roles[rank]

    def live_role_endpoints(self, role):
        """Live endpoints holding ``role`` — the prefill side's decode-
        peer pick and the role-aware autoscaler both route through this."""
        return [self.endpoints[r] for r in sorted(self.live)
                if self.role_of(r) == role]

    def live_role_ranks(self, role):
        return [r for r in sorted(self.live) if self.role_of(r) == role]

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self.server.attach_fleet(self)
        if self.is_coordinator():
            self._become_coordinator(initial=True)
        else:
            self.server.set_alive(self.epoch, False)
        self._start_heartbeat()
        return self

    def _become_coordinator(self, initial=False):
        timeout = float(_flag("serving_hb_timeout") or 2.0)
        if not initial:
            # promotion: rebuild liveness from a fresh probe of the list
            self.live = {r for r, ep in enumerate(self.endpoints)
                         if r == self.rank
                         or _rpc.probe(ep, key=codec.ALIVE_KEY,
                                       timeout=1.0) is not None}
            self.epoch += 1
            _tm.inc("serving_fleet_promotions_total")
            logging.warning("[serving-fleet] rank %d promoted to "
                            "coordinator (live=%s)", self.rank,
                            sorted(self.live))
        self._coord_rank = self.rank
        self.mon = HeartBeatMonitor(
            0, timeout_s=timeout, name="serving-fleet",
            worker_ids=sorted(self.live - {self.rank}))
        self.server.set_alive(self.epoch, True)
        self._publish_view()
        # heartbeats only wake the poll loop while peers are alive; a
        # self-tick keeps eviction checks running even with a silent fleet
        if self._tick_thread is None:
            self._tick_thread = threading.Thread(
                target=self._self_tick, name="fleet-tick", daemon=True)
            self._tick_thread.start()

    def _self_tick(self):
        interval = float(_flag("serving_hb_interval") or 0.3)
        me = self.endpoints[self.rank]
        while not self._stop.wait(interval):
            if not self.is_coordinator():
                continue
            try:
                c = _rpc.RpcClient(me, connect_timeout=1.0,
                                   rpc_deadline=2.0, retry_times=0)
                try:
                    c.send_var(FLEET_HB + str(self.rank),
                               np.asarray([self.rank], np.int64))
                finally:
                    c.close()
            except Exception:
                pass

    def _start_heartbeat(self):
        def loop():
            interval = float(_flag("serving_hb_interval") or 0.3)
            client = None
            while not self._stop.wait(interval):
                if self.is_coordinator():
                    continue
                try:
                    if client is None:
                        client = _rpc.RpcClient(
                            self.endpoints[self._coord_rank],
                            connect_timeout=1.0, rpc_deadline=2.0,
                            retry_times=0)
                    client.send_var(FLEET_HB + str(self.rank),
                                    np.asarray([self.rank], np.int64))
                    self._hb_failures = 0
                except Exception:
                    client = None
                    self._hb_failures += 1
                    if self._hb_failures >= _PROMOTE_AFTER:
                        self._hb_failures = 0
                        self._coordinator_lost()

        self._hb_thread = threading.Thread(target=loop, name="fleet-hb",
                                           daemon=True)
        self._hb_thread.start()

    def _coordinator_lost(self):
        """The coordinator stopped answering: lowest live rank takes over."""
        for r in sorted(self.live):
            if r == self.rank:
                break
            if r == self._coord_rank:
                continue
            if _rpc.probe(self.endpoints[r], key=codec.ALIVE_KEY,
                          timeout=1.0) is not None:
                self.live.discard(self._coord_rank)
                self._coord_rank = r
                return
        self.live.discard(self._coord_rank)
        self._become_coordinator()

    # -- event stream (called from the server poll loop) ---------------------

    def on_event(self, name, arr):
        if name.startswith(FLEET_HB) and self.mon is not None:
            r = int(arr[0])
            if r in self.live:
                self.mon.update(r)
            elif r != self.rank and r not in self._retiring:
                # a relaunched/late replica re-announces itself (a
                # RETIRING rank's last heartbeats must NOT re-add it —
                # the set clears when the autoscaler reuses the slot)
                self.live.add(r)
                self.mon.update(r)
                with self._lock:
                    self._pending_view = True

    def tick(self):
        """Eviction check + deferred view publication.  Runs on the poll
        loop after every event AND on the engine's batch-boundary hook, so
        a shrink always lands between batches."""
        if not self.is_coordinator() or self.mon is None:
            return
        dead = [r for r in self.mon.check() if r in self.live]
        if dead:
            for r in dead:
                self.live.discard(r)
                self.mon.remove(r)
            self.epoch += 1
            _tm.inc("serving_fleet_evictions_total", len(dead))
            _tm.event("serving_fleet_evict", dead=dead, epoch=self.epoch,
                      live=sorted(self.live),
                      roles=[self.role_of(r) for r in dead])
            logging.warning("[serving-fleet] epoch %d: evicted %s (%s), "
                            "live=%s", self.epoch, dead,
                            ",".join(self.role_of(r) for r in dead),
                            sorted(self.live))
            with self._lock:
                self._pending_view = True
        publish = False
        with self._lock:
            if self._pending_view and not self.server.engine.in_batch:
                self._pending_view = False
                publish = True
        if publish:
            self._publish_view()

    def _publish_view(self):
        ranks = sorted(self.live)
        live_eps = [self.endpoints[r] for r in ranks]
        live_roles = [self.role_of(r) for r in ranks] \
            if self.roles is not None else None
        self.server.rpc.set_var(
            FLEET_VIEW,
            np.asarray([self.epoch] + ranks, np.int64))
        if self.endpoints_file:
            try:
                write_endpoints_file(self.endpoints_file, self.epoch,
                                     live_eps, rollout=self.rollout_doc,
                                     roles=live_roles)
            except OSError as e:
                logging.warning("[serving-fleet] endpoints file write "
                                "failed: %s", e)
        _tm.set_gauge("serving_fleet_size", len(self.live))
        _tm.set_gauge("serving_fleet_epoch", self.epoch)
        if self.roles is not None:
            for role in ("prefill", "decode", "serve"):
                n = sum(1 for r in ranks if self.role_of(r) == role)
                if n or role != "serve":
                    _tm.set_gauge("serving_fleet_role_size", n, role=role)

    # -- control plane (autoscaler / rollout) --------------------------------

    def publish_rollout(self, doc):
        """Version-routing change: ride the next epoch bump so every
        client re-reading the endpoints file sees it atomically with the
        membership view."""
        self.rollout_doc = doc
        self.epoch += 1
        with self._lock:
            self._pending_view = True
        self.tick()

    def retire(self, rank):
        """Graceful scale-down of one replica: drop it from the view
        FIRST (clients stop routing to it), then order it to drain and
        exit via ``__retire__``.  Its last heartbeats are ignored via
        the retiring set so it can't flap back in."""
        if rank == self.rank or rank not in self.live:
            return False
        self.live.discard(rank)
        self._retiring.add(rank)
        if self.mon is not None:
            self.mon.remove(rank)
        self.epoch += 1
        _tm.event("serving_fleet_retire", rank=rank, epoch=self.epoch,
                  role=self.role_of(rank))
        logging.warning("[serving-fleet] epoch %d: retiring rank %d (%s)",
                        self.epoch, rank, self.role_of(rank))
        with self._lock:
            self._pending_view = True
        self.tick()
        try:
            c = _rpc.RpcClient(self.endpoints[rank], connect_timeout=1.0,
                               rpc_deadline=3.0, retry_times=0)
            try:
                c.send_var(codec.RETIRE_KEY,
                           np.asarray([self.rank], np.int64))
            finally:
                c.close()
        except Exception:
            pass  # already dead: eviction bookkeeping is done anyway
        return True

    def notice_relaunch(self, rank):
        """The autoscaler reused a retired slot: accept its heartbeats
        again."""
        self._retiring.discard(rank)

    def view(self):
        v = {"epoch": self.epoch, "live": sorted(self.live),
             "coordinator": self._coord_rank,
             "retiring": sorted(self._retiring)}
        if self.roles is not None:
            v["roles"] = {r: self.role_of(r) for r in sorted(self.live)}
        return v

    def stop(self):
        self._stop.set()


class AutoScaler:
    """Replica-count controller (coordinator-side).

    Watches queue depth and shed rate (``metrics_fn`` — in production a
    closure over the engine gauges + scraped peers, in tests any stub)
    and drives ``scale_up_fn`` / ``scale_down_fn`` (tools/serve.py wires
    these to "fork a prewarmed standby into the lowest dead rank slot"
    and "fleet.retire(highest non-coordinator live rank)").

    Flap protection is layered: PRESSURE must persist for
    ``FLAGS_serving_scale_up_ticks`` consecutive observations (and idle
    for ``FLAGS_serving_scale_down_ticks``) before acting, any event
    starts a ``FLAGS_serving_autoscale_cooldown``-tick refractory
    window, and the replica count is clamped to
    [FLAGS_serving_min_replicas, FLAGS_serving_max_replicas].  A
    one-tick metrics blip therefore never moves the fleet — the unit
    tests assert exactly that."""

    def __init__(self, metrics_fn, scale_up_fn, scale_down_fn,
                 replicas_fn, min_replicas=None, max_replicas=None,
                 up_ticks=None, down_ticks=None, cooldown=None,
                 up_depth=None, interval_s=None, pressure_fn=None):
        self.metrics_fn = metrics_fn
        self.scale_up_fn = scale_up_fn
        self.scale_down_fn = scale_down_fn
        self.replicas_fn = replicas_fn
        # role-specific pressure signal: callable(metrics) -> (pressure,
        # idle) booleans, replacing the default queue-depth/shed-delta
        # rule — a disaggregated fleet runs one AutoScaler per role
        # (prefill keyed on queue depth / TTFT, decode on KV-pool
        # occupancy / ITL) with everything else (streaks, cooldown,
        # clamps) shared
        self.pressure_fn = pressure_fn

        def _default(v, flag, cast):
            return cast(v if v is not None else _flag(flag))

        self.min_replicas = _default(min_replicas,
                                     "serving_min_replicas", int)
        self.max_replicas = _default(max_replicas,
                                     "serving_max_replicas", int)
        self.up_ticks = _default(up_ticks, "serving_scale_up_ticks", int)
        self.down_ticks = _default(down_ticks,
                                   "serving_scale_down_ticks", int)
        self.cooldown_ticks = _default(cooldown,
                                       "serving_autoscale_cooldown", int)
        self.up_depth = _default(up_depth, "serving_scale_up_depth", float)
        self.interval_s = _default(interval_s,
                                   "serving_autoscale_interval", float)
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = 0
        self._last_shed = None
        self._race_logged = False
        self.events = []                # ("up"|"down", tick_no) history
        self._ticks = 0
        self._stop = threading.Event()
        self._thread = None

    def tick(self):
        """One observation -> maybe one scaling event.  Returns
        "up" | "down" | None (tests drive this directly)."""
        self._ticks += 1
        try:
            m = self.metrics_fn() or {}
        except Exception:
            # scrape raced a membership change: the tick is skipped, but
            # a flapping endpoints file must not read as an unexplained
            # scaling stall — count every race, log the first
            _tm.inc("autoscale_scrape_races_total")
            if not self._race_logged:
                self._race_logged = True
                logging.warning("[autoscale] metrics scrape raced a "
                                "membership change; skipping tick "
                                "(counted in autoscale_scrape_races_total,"
                                " logged once)")
            return None
        depth = float(m.get("queue_depth", 0.0))
        shed = float(m.get("shed_total", 0.0))
        shed_delta = 0.0 if self._last_shed is None \
            else max(shed - self._last_shed, 0.0)
        self._last_shed = shed
        if self._cooldown > 0:
            # refractory window after an event: observe (the shed
            # baseline above keeps advancing) but never act or build
            # streaks, so one overload burst maps to ONE scale-up
            self._cooldown -= 1
            self._up_streak = self._down_streak = 0
            return None
        if self.pressure_fn is not None:
            pressure, idle = self.pressure_fn(m)
        else:
            # a fleet-windowed shed rate (shed/s over the rate window,
            # from FleetMonitor) subsumes the local one-tick shed delta:
            # it survives replica restarts and catches sheds on peers
            # the coordinator's own counter never sees
            if "shed_rate" in m:
                shedding = float(m.get("shed_rate", 0.0)) > 0.0
            else:
                shedding = shed_delta > 0.0
            pressure = depth >= self.up_depth or shedding
            idle = depth <= 0.0 and not shedding
        if pressure:
            self._up_streak += 1
            self._down_streak = 0
        elif idle:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        n = int(self.replicas_fn())
        if self._up_streak >= self.up_ticks and n < self.max_replicas:
            self._fire("up", self.scale_up_fn)
            return "up"
        if self._down_streak >= self.down_ticks and n > self.min_replicas:
            self._fire("down", self.scale_down_fn)
            return "down"
        return None

    def _fire(self, direction, fn):
        self._up_streak = self._down_streak = 0
        self._cooldown = self.cooldown_ticks
        self.events.append((direction, self._ticks))
        _tm.inc("autoscale_events_total", dir=direction)
        _tm.event("autoscale", dir=direction, tick=self._ticks)
        logging.warning("[autoscale] scale %s at tick %d", direction,
                        self._ticks)
        try:
            fn()
        except Exception:
            logging.exception("[autoscale] scale_%s failed", direction)

    def start(self):
        def loop():
            while not self._stop.wait(self.interval_s):
                self.tick()

        self._thread = threading.Thread(target=loop, name="autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
