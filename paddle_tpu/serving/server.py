"""RPC serving frontend: wire protocol over native/rpc.py.

One ``RpcServer`` per replica carries the whole protocol:

  ``__infer__:<req_id>``  inbound SEND: packed request (serving/codec.py);
                          the reply is published as ``__reply__:<req_id>``
                          and the client's blocking GET picks it up (the
                          transport parks GETs until the var exists)
  ``__alive__``           [rank, epoch, is_coordinator] — same probe
                          contract as the elastic control plane
  ``__metrics__``         telemetry snapshot, republished every second
                          (core/telemetry.start_publisher) for
                          tools/metrics_dump.py --scrape
  ``__spec__:<model>``    feed/fetch signature + buckets, so loadgen can
                          synthesize valid requests without the model dir
  ``__fhb__<rank>``       fleet replica heartbeats (serving/fleet.py)
  ``__generate__:<id>``   inbound SEND: autoregressive request for the
                          paged-KV decode engine; generated tokens stream
                          as ``__stream__:<id>:<k>`` chunks and the final
                          reply still lands on ``__reply__:<id>``
  ``__abort__:<id>``      inbound SEND: drop the sequence, free its KV
                          blocks (client timeout-replay abandonment)
  ``__rollout__``         this replica's applied version-routing state
                          (always published, empty when no rollout —
                          chaos tests GET it from every survivor)
  ``__rollout_set__``     coordinator broadcast: adopt a routing state
  ``__rollout_ctl__:<id>`` admin command for the RolloutController; the
                          reply lands on ``__reply__:<id>``
  ``__retire__``          coordinator order: drain both engines at a
                          batch boundary, then hand off to ``on_retire``
                          (tools/serve.py exits the process)

Replies are garbage-collected FIFO beyond a bounded ring — a crashed
client can never grow the server's var store unboundedly.

Chaos hooks: the named fault points ``serving.infer`` /
``serving.generate`` / ``serving.reply`` (utils/fault_injection.py,
armed by FLAGS_fault_spec) sit on the wire path — ``drop`` loses the
frame, ``error`` substitutes an error reply — so serving tests inject
faults without SIGKILLing processes.
"""

import threading

import numpy as np

from ..core import telemetry as _tm
from ..core import tracing as _tr
from ..native.rpc import EV_SEND, RpcServer
from ..utils.fault_injection import maybe_fail
from . import codec

__all__ = ["ServingServer"]

_REPLY_RING = 1024


class ServingServer:
    def __init__(self, engine, port=0, rank=0, decode_engine=None):
        self.engine = engine
        self.decode_engine = decode_engine
        self.rank = int(rank)
        self.rpc = RpcServer(port=port)
        self.port = self.rpc.port
        self.fleet = None
        self.rollout = None            # RolloutController (coordinator)
        self.on_retire = None          # callback after a __retire__ drain
        self._retire_thread = None
        self._reply_keys = []
        self._reply_lock = threading.Lock()
        self._thread = None
        self._pub_stop = None
        self._stopped = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self.engine.start()
        self.rpc.set_var(codec.ALIVE_KEY,
                         np.asarray([self.rank, 0, 0], np.int64))
        # always published (empty before any rollout) so a chaos test's
        # GET never parks forever on a replica that missed every flip
        self.rpc.set_var(codec.ROLLOUT_KEY, codec.pack({"models": {}}))
        for name in self.engine.models():
            self.rpc.set_var(codec.SPEC_KEY + name,
                             codec.pack(self.engine.spec(name)))
        if self.decode_engine is not None:
            self.decode_engine.start()
            for name in self.decode_engine.models():
                self.rpc.set_var(codec.SPEC_KEY + name,
                                 codec.pack(self.decode_engine.spec(name)))
        self.rpc.serve(True)
        if _tm.enabled():
            self._pub_stop = _tm.start_publisher(self.rpc, interval_s=1.0)
        self._thread = threading.Thread(target=self._poll_loop,
                                        name="serving-rpc", daemon=True)
        self._thread.start()
        return self

    def attach_fleet(self, fleet):
        """Wire a serving fleet: its heartbeats arrive on this server's
        event stream, and membership changes publish at batch boundaries
        via the engine hook."""
        self.fleet = fleet
        self.engine.on_batch_boundary = fleet.tick
        if self.decode_engine is not None:
            self.decode_engine.on_batch_boundary = fleet.tick

    def _poll_loop(self):
        while True:
            t, name, arr = self.rpc.poll()
            if t == 0:
                return
            if t != EV_SEND or name is None:
                continue
            if name.startswith(codec.INFER_KEY):
                self._on_infer(name[len(codec.INFER_KEY):], arr)
            elif name.startswith(codec.GEN_KEY):
                self._on_generate(name[len(codec.GEN_KEY):], arr)
            elif name.startswith(codec.ABORT_KEY):
                if self.decode_engine is not None:
                    self.decode_engine.abort(name[len(codec.ABORT_KEY):])
            elif name == codec.ROLLOUT_SET_KEY:
                self._on_rollout_set(arr)
            elif name.startswith(codec.ROLLOUT_CTL_KEY):
                self._on_rollout_ctl(
                    name[len(codec.ROLLOUT_CTL_KEY):], arr)
            elif name == codec.RETIRE_KEY:
                self._on_retire()
            elif self.fleet is not None:
                self.fleet.on_event(name, arr)
            if self.fleet is not None:
                self.fleet.tick()

    def _on_infer(self, req_id, arr):
        from .engine import InferReply

        fault = maybe_fail("serving.infer")
        if fault == "drop":
            return                     # frame lost: client replays
        if fault == "error":
            self._publish(req_id, InferReply(
                "error", error="injected fault: serving.infer"))
            return
        try:
            meta, arrays = codec.unpack(arr)
            feeds = dict(zip(meta["feeds"], arrays))
        except Exception as e:
            self._publish(req_id, None)
            _tm.inc("serving_bad_request_total")
            del e
            return
        tp = meta.get(codec.TRACEPARENT)
        # the admission span parents under the client's root span (wire
        # context), and engine.submit opens the request span inside it
        with _tr.remote_parent(tp):
            with _tr.span("serving.admission", req_id=req_id,
                          model=meta.get("model", ""), rank=self.rank):
                self.engine.submit(
                    meta.get("model", ""), feeds,
                    tenant=meta.get("tenant", "default"),
                    deadline_ms=meta.get("deadline_ms"),
                    req_id=req_id,
                    traceparent=tp,
                    tier=meta.get(codec.TIER),
                    callback=lambda pending: self._publish(
                        pending.req_id, pending.reply, pending))

    def _on_generate(self, req_id, arr):
        from .engine import InferReply

        fault = maybe_fail("serving.generate")
        if fault == "drop":
            return
        if fault == "error":
            self._publish(req_id, InferReply(
                "error", error="injected fault: serving.generate"))
            return
        try:
            meta, arrays = codec.unpack(arr)
            prompt = arrays[0]
        except Exception:
            self._publish(req_id, None)
            _tm.inc("serving_bad_request_total")
            return
        if self.decode_engine is None:
            self._publish(req_id, InferReply(
                "error", error="replica has no decode engine"))
            return
        stream = bool(meta.get("stream"))
        on_token = self._stream_publisher(req_id) if stream else None
        tp = meta.get(codec.TRACEPARENT)
        with _tr.remote_parent(tp):
            with _tr.span("serving.admission", req_id=req_id, decode=True,
                          model=meta.get("model", ""), rank=self.rank):
                self.decode_engine.submit(
                    meta.get("model", ""), prompt,
                    max_new_tokens=int(meta.get("max_new_tokens", 16)),
                    tenant=meta.get("tenant", "default"),
                    deadline_ms=meta.get("deadline_ms"),
                    eos_id=int(meta.get("eos_id", -1)),
                    req_id=req_id,
                    traceparent=tp,
                    tier=meta.get(codec.TIER),
                    on_token=on_token,
                    callback=lambda pending: self._publish(
                        pending.req_id, pending.reply, pending))

    def _stream_publisher(self, req_id):
        """Per-token chunk publisher: ``__stream__:<id>:<k>`` carries the
        k-th generated token; the final/terminal chunk sets done.  Chunk
        keys join the reply GC ring so crashed streamers can't leak."""

        def on_token(rid, index, token, done, status):
            key = "%s%s:%d" % (codec.STREAM_KEY, rid, index)
            self.rpc.set_var(key, codec.pack(
                {"i": int(index), "done": bool(done), "status": status,
                 "token": None if token is None else int(token)}))
            with self._reply_lock:
                self._reply_keys.append(key)
                while len(self._reply_keys) > _REPLY_RING:
                    self.rpc.del_var(self._reply_keys.pop(0))
        return on_token

    def _publish(self, req_id, reply, pending=None):
        from .engine import InferReply

        fault = maybe_fail("serving.reply")
        if fault == "drop":
            return                     # reply lost: client GET times out
        if reply is None:
            reply = InferReply("error", error="malformed request")
        if fault == "error":
            reply = InferReply("error",
                               error="injected fault: serving.reply")
        # runs inside _Pending.complete(), so parent explicitly under the
        # request span rather than whatever is on the completing thread
        with _tr.span("serving.reply_publish",
                      parent=getattr(pending, "span", None),
                      req_id=req_id, status=reply.status):
            meta = reply.to_meta()
            tp = getattr(pending, "traceparent", None)
            if tp:
                meta[codec.TRACEPARENT] = tp
            names = list(reply.outputs)
            buf = codec.pack(meta, [reply.outputs[n] for n in names])
            key = codec.REPLY_KEY + req_id
            self.rpc.set_var(key, buf)
        with self._reply_lock:
            self._reply_keys.append(key)
            while len(self._reply_keys) > _REPLY_RING:
                self.rpc.del_var(self._reply_keys.pop(0))

    # -- control plane -------------------------------------------------------

    def apply_rollout(self, doc):
        """Adopt a version-routing state (local command or coordinator
        ``__rollout_set__`` broadcast) and republish this replica's view
        under ``__rollout__`` — the chaos leg asserts every survivor
        converges to the same doc."""
        self.engine.apply_routes(doc.get("models") or {})
        self.rpc.set_var(codec.ROLLOUT_KEY,
                         codec.pack({"models": self.engine.routes()}))

    def _on_rollout_set(self, arr):
        try:
            doc, _ = codec.unpack(arr)
        except Exception:
            _tm.inc("serving_bad_request_total")
            return
        self.apply_rollout(doc)

    def _on_rollout_ctl(self, req_id, arr):
        from .engine import InferReply

        try:
            cmd, _ = codec.unpack(arr)
        except Exception:
            self._publish(req_id, None)
            _tm.inc("serving_bad_request_total")
            return
        if self.rollout is None:
            reply = InferReply("error",
                               error="replica has no rollout controller")
        else:
            meta = self.rollout.handle(cmd)
            reply = InferReply(meta.get("status", "error"),
                               error=meta.get("error"))
            reply.phases = {k: v for k, v in meta.items()
                            if k not in ("status", "error")}
        self._publish(req_id, reply)

    def _on_retire(self):
        """Drain both engines at a batch boundary on a side thread (the
        poll loop must keep serving queued work), then fire on_retire."""
        if self._retire_thread is not None:
            return

        def drain():
            self.engine.drain()
            if self.decode_engine is not None:
                self.decode_engine.drain()
            _tm.event("serving_retired", rank=self.rank)
            if self.on_retire is not None:
                self.on_retire()

        self._retire_thread = threading.Thread(
            target=drain, name="serving-retire", daemon=True)
        self._retire_thread.start()

    def set_alive(self, epoch, is_coordinator):
        self.rpc.set_var(codec.ALIVE_KEY, np.asarray(
            [self.rank, int(epoch), 1 if is_coordinator else 0], np.int64))

    def shutdown(self):
        if self._stopped.is_set():
            return
        self._stopped.set()
        if self._pub_stop is not None:
            # stop AND join (idempotent) — a leaked publisher thread
            # would republish __metrics__ into the next test's server
            self._pub_stop.stop()
        if self.rollout is not None:
            self.rollout.stop()
        if self.fleet is not None:
            self.fleet.stop()
        self.engine.stop()
        if self.decode_engine is not None:
            self.decode_engine.stop()
        self.rpc.shutdown()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
