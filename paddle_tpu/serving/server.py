"""RPC serving frontend: wire protocol over native/rpc.py.

One ``RpcServer`` per replica carries the whole protocol:

  ``__infer__:<req_id>``  inbound SEND: packed request (serving/codec.py);
                          the reply is published as ``__reply__:<req_id>``
                          and the client's blocking GET picks it up (the
                          transport parks GETs until the var exists)
  ``__alive__``           [rank, epoch, is_coordinator] — same probe
                          contract as the elastic control plane
  ``__metrics__``         telemetry snapshot, republished every second
                          (core/telemetry.start_publisher) for
                          tools/metrics_dump.py --scrape
  ``__spec__:<model>``    feed/fetch signature + buckets, so loadgen can
                          synthesize valid requests without the model dir
  ``__fhb__<rank>``       fleet replica heartbeats (serving/fleet.py)
  ``__generate__:<id>``   inbound SEND: autoregressive request for the
                          paged-KV decode engine; generated tokens stream
                          as ``__stream__:<id>:<k>`` chunks and the final
                          reply still lands on ``__reply__:<id>``
  ``__abort__:<id>``      inbound SEND: drop the sequence, free its KV
                          blocks (client timeout-replay abandonment)
  ``__rollout__``         this replica's applied version-routing state
                          (always published, empty when no rollout —
                          chaos tests GET it from every survivor)
  ``__rollout_set__``     coordinator broadcast: adopt a routing state
  ``__rollout_ctl__:<id>`` admin command for the RolloutController; the
                          reply lands on ``__reply__:<id>``
  ``__retire__``          coordinator order: drain both engines at a
                          batch boundary, then hand off to ``on_retire``
                          (tools/serve.py exits the process); with
                          FLAGS_migrate_on_drain the decode drain pushes
                          live sessions to peers instead of waiting them
                          out (serving/migrate.py)
  ``__resume__:<id>``     inbound SEND: client crash-resume — prompt +
                          already-received tokens; the replica resumes
                          decode at position p, re-prefilling only what
                          its prefix/history index does not hold, and
                          acks under ``__resumeack__:<id>``

Replies are garbage-collected FIFO beyond a bounded ring — a crashed
client can never grow the server's var store unboundedly.

Chaos hooks: the named fault points ``serving.infer`` /
``serving.generate`` / ``serving.reply`` (utils/fault_injection.py,
armed by FLAGS_fault_spec) sit on the wire path — ``drop`` loses the
frame, ``error`` substitutes an error reply — so serving tests inject
faults without SIGKILLing processes.

Disaggregated roles (``role=`` / serving/disagg.py): a ``prefill``-role
replica answers ``__generate__`` by picking a decode peer, publishing
``__pair__:<id>``, and running handoff prefill — sealed blocks stream to
the peer as ``__kvxfer__`` frames and a commit frame delegates
generation; a ``decode``-role replica adopts inbound blocks into its
pool and serves the stream/reply for committed requests.  Either role
still serves plain monolith traffic (the pair var's ``{"decode": None}``
is the no-peers fallback).
"""

import threading
import time

import numpy as np

from ..core import telemetry as _tm
from ..core import tracing as _tr
from ..native.rpc import EV_SEND, RpcServer
from ..utils.fault_injection import maybe_fail
from . import codec

__all__ = ["ServingServer"]

_REPLY_RING = 1024


class ServingServer:
    def __init__(self, engine, port=0, rank=0, decode_engine=None,
                 role=None, decode_peers=None):
        self.engine = engine
        self.decode_engine = decode_engine
        self.rank = int(rank)
        self.role = role or "serve"
        if self.role not in ("serve", "prefill", "decode"):
            raise ValueError("serving role must be serve|prefill|decode, "
                             "got %r" % (role,))
        self.rpc = RpcServer(port=port)
        self.port = self.rpc.port
        self.fleet = None
        self.rollout = None            # RolloutController (coordinator)
        self.on_retire = None          # callback after a __retire__ drain
        self._retire_thread = None
        self._reply_keys = []
        self._reply_lock = threading.Lock()
        self._thread = None
        self._pub_stop = None
        self._stopped = threading.Event()
        # disaggregation state: the prefill side's sealed-block sender +
        # req -> pair registry; the decode side's adoption tracker
        self._decode_peers_static = list(decode_peers or [])
        self._xfer = None              # KVBlockSender (prefill role)
        self._adopt = None             # AdoptTracker (decode role)
        self._pairs = {}               # req_id -> request meta (prefill)
        self._pair_lock = threading.Lock()
        self._pair_rr = 0
        # live session migration (serving/migrate.py): source-side
        # pusher + destination-side tail/digest holding buffer
        self.migrator = None           # SessionMigrator
        self._resume_buf = None        # ResumeBuffer
        self.fleetmon = None           # FleetMonitor (tools/serve.py)

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self.engine.start()
        self.rpc.set_var(codec.ALIVE_KEY,
                         np.asarray([self.rank, 0, 0], np.int64))
        # always published (empty before any rollout) so a chaos test's
        # GET never parks forever on a replica that missed every flip
        self.rpc.set_var(codec.ROLLOUT_KEY, codec.pack({"models": {}}))
        for name in self.engine.models():
            self.rpc.set_var(codec.SPEC_KEY + name,
                             codec.pack(self.engine.spec(name)))
        if self.decode_engine is not None:
            self.decode_engine.start()
            for name in self.decode_engine.models():
                self.rpc.set_var(codec.SPEC_KEY + name,
                                 codec.pack(self.decode_engine.spec(name)))
        if self.decode_engine is not None and self.role == "prefill":
            from .disagg import KVBlockSender

            self._xfer = KVBlockSender()
            self.decode_engine.on_block_sealed = self._on_block_sealed
            self.decode_engine.on_handoff = self._on_handoff
        if self.decode_engine is not None and self.role == "decode":
            from .disagg import AdoptTracker

            self._adopt = AdoptTracker(self._on_orphan)
        if self.decode_engine is not None:
            from .. import flags

            if flags.flag("session_migration"):
                from .migrate import ResumeBuffer, SessionMigrator

                self._resume_buf = ResumeBuffer()
                self.migrator = SessionMigrator(
                    self.decode_engine, peers_fn=self._migration_peers,
                    occupancy_fn=self._peer_occupancy)
                if flags.flag("migrate_on_pressure"):
                    self.decode_engine.on_preempt = self._on_preempt
        self.rpc.serve(True)
        if _tm.enabled():
            self._pub_stop = _tm.start_publisher(
                self.rpc, interval_s=1.0, on_publish=self._pre_publish)
        self._thread = threading.Thread(target=self._poll_loop,
                                        name="serving-rpc", daemon=True)
        self._thread.start()
        return self

    def _pre_publish(self):
        """Derived per-window gauges, recomputed on every 1s republish
        (runs inside the publisher tick, after series_record): per-tier
        windowed shed RATE from the tier-labeled counter's series
        deltas, and per-namespace prefix hit rate from the
        namespace-labeled token counters — the windowed signals the
        autoscaler's tier policy and the prefix-aware router bias on."""
        from .. import flags

        window = float(flags.flag("serving_rate_window"))
        for flat, labels in _tm.label_sets("serving_tier_shed_total"):
            _tm.set_gauge("serving_tier_shed_rate",
                          _tm.series_rate(flat, window),
                          tier=labels.get("tier", "default"))
        for flat, labels in _tm.label_sets(
                "prefix_cache_ns_lookup_tokens_total"):
            ns = labels.get("namespace", "default")
            lookups = _tm.series_rate(flat, window)
            hits = _tm.series_rate(
                "prefix_cache_ns_hit_tokens_total{namespace=%s}" % ns,
                window)
            _tm.set_gauge("prefix_cache_ns_hit_rate",
                          hits / lookups if lookups > 0 else 0.0,
                          namespace=ns)

    def attach_fleet(self, fleet):
        """Wire a serving fleet: its heartbeats arrive on this server's
        event stream, and membership changes publish at batch boundaries
        via the engine hook."""
        self.fleet = fleet
        self.engine.on_batch_boundary = fleet.tick
        if self.decode_engine is not None:
            self.decode_engine.on_batch_boundary = fleet.tick

    def _poll_loop(self):
        while True:
            try:
                t, name, arr = self.rpc.poll()
            except ConnectionError:
                return             # transport torn down under the loop
            if t == 0:
                return
            if t != EV_SEND or name is None:
                continue
            if self._stopped.is_set():
                return             # late frame raced shutdown(): drop it
            if name.startswith(codec.INFER_KEY):
                self._on_infer(name[len(codec.INFER_KEY):], arr)
            elif name.startswith(codec.GEN_KEY):
                self._on_generate(name[len(codec.GEN_KEY):], arr)
            elif name.startswith(codec.ABORT_KEY):
                rid = name[len(codec.ABORT_KEY):]
                if self.decode_engine is not None:
                    self.decode_engine.abort(rid)
                self._reconcile_abort(rid)
            elif name.startswith(codec.KVXFER_KEY):
                self._on_kvxfer(name[len(codec.KVXFER_KEY):], arr)
            elif name.startswith(codec.RESUME_KEY):
                self._on_resume(name[len(codec.RESUME_KEY):], arr)
            elif name == codec.ROLLOUT_SET_KEY:
                self._on_rollout_set(arr)
            elif name.startswith(codec.ROLLOUT_CTL_KEY):
                self._on_rollout_ctl(
                    name[len(codec.ROLLOUT_CTL_KEY):], arr)
            elif name == codec.RETIRE_KEY:
                self._on_retire()
            elif self.fleet is not None:
                self.fleet.on_event(name, arr)
            if self.fleet is not None:
                self.fleet.tick()

    def _on_infer(self, req_id, arr):
        from .engine import InferReply

        fault = maybe_fail("serving.infer")
        if fault == "drop":
            return                     # frame lost: client replays
        if fault == "error":
            self._publish(req_id, InferReply(
                "error", error="injected fault: serving.infer"))
            return
        try:
            meta, arrays = codec.unpack(arr)
            feeds = dict(zip(meta["feeds"], arrays))
        except Exception as e:
            self._publish(req_id, None)
            _tm.inc("serving_bad_request_total")
            del e
            return
        tp = meta.get(codec.TRACEPARENT)
        # the admission span parents under the client's root span (wire
        # context), and engine.submit opens the request span inside it
        with _tr.remote_parent(tp):
            with _tr.span("serving.admission", req_id=req_id,
                          model=meta.get("model", ""), rank=self.rank):
                self.engine.submit(
                    meta.get("model", ""), feeds,
                    tenant=meta.get("tenant", "default"),
                    deadline_ms=meta.get("deadline_ms"),
                    req_id=req_id,
                    traceparent=tp,
                    tier=meta.get(codec.TIER),
                    callback=lambda pending: self._publish(
                        pending.req_id, pending.reply, pending))

    def _on_generate(self, req_id, arr):
        from .engine import InferReply

        fault = maybe_fail("serving.generate")
        if fault == "drop":
            return
        if fault == "error":
            self._publish(req_id, InferReply(
                "error", error="injected fault: serving.generate"))
            return
        try:
            meta, arrays = codec.unpack(arr)
            prompt = arrays[0]
        except Exception:
            self._publish(req_id, None)
            _tm.inc("serving_bad_request_total")
            return
        if self.decode_engine is None:
            self._publish(req_id, InferReply(
                "error", error="replica has no decode engine"))
            return
        if self.role == "prefill" and self._try_handoff(req_id, meta,
                                                        prompt):
            return
        stream = bool(meta.get("stream"))
        on_token = self._stream_publisher(req_id) if stream else None
        tp = meta.get(codec.TRACEPARENT)
        with _tr.remote_parent(tp):
            with _tr.span("serving.admission", req_id=req_id, decode=True,
                          model=meta.get("model", ""), rank=self.rank):
                self.decode_engine.submit(
                    meta.get("model", ""), prompt,
                    max_new_tokens=int(meta.get("max_new_tokens", 16)),
                    tenant=meta.get("tenant", "default"),
                    deadline_ms=meta.get("deadline_ms"),
                    eos_id=int(meta.get("eos_id", -1)),
                    req_id=req_id,
                    traceparent=tp,
                    tier=meta.get(codec.TIER),
                    on_token=on_token,
                    callback=lambda pending: self._publish(
                        pending.req_id, pending.reply, pending))

# -- disaggregated prefill/decode --------------------------------------------

    def _advertised_ep(self):
        """This replica's endpoint as decode peers should probe it."""
        if self.fleet is not None and self.rank < len(self.fleet.endpoints):
            return self.fleet.endpoints[self.rank]
        return "127.0.0.1:%d" % self.port

    def _pick_decode_peer(self):
        """Round-robin over live decode-role endpoints (fleet view when
        attached, else the static ``decode_peers`` list)."""
        peers = []
        if self.fleet is not None:
            peers = self.fleet.live_role_endpoints("decode")
        if not peers:
            peers = list(self._decode_peers_static)
        if not peers:
            return None
        self._pair_rr += 1
        return peers[self._pair_rr % len(peers)]

    def _migration_peers(self):
        """Candidate endpoints for a session push: every live replica
        that runs a decode engine (decode + monolith roles; prefill-only
        replicas can't resume), minus this one.  Falls back to the
        static decode_peers list when no fleet is attached."""
        me = self._advertised_ep()
        peers = []
        if self.fleet is not None:
            for role in ("decode", "serve"):
                peers.extend(self.fleet.live_role_endpoints(role))
        if not peers:
            peers = list(self._decode_peers_static)
        return [p for p in dict.fromkeys(peers) if p != me]

    def _peer_occupancy(self):
        """endpoint -> windowed KV occupancy from the last fleet doc
        (fleetmon rows), so the migrator prefers the least-loaded
        survivor.  Empty when no monitor is attached."""
        mon = self.fleetmon
        doc = getattr(mon, "last", None) if mon is not None else None
        if not doc:
            return {}
        return {r["endpoint"]: float(r.get("kv_occupancy", 0.0))
                for r in doc.get("replicas", []) if r.get("up")}

    def _wire_dtype(self, model):
        m = self.decode_engine._models.get(model)
        return m.kv_config.dtype if m is not None else "f32"

    def _publish_pair(self, req_id, peer):
        key = codec.PAIR_KEY + req_id
        self.rpc.set_var(key, codec.pack({"decode": peer}))
        with self._reply_lock:
            self._reply_keys.append(key)
            while len(self._reply_keys) > _REPLY_RING:
                self.rpc.del_var(self._reply_keys.pop(0))

    def _try_handoff(self, req_id, meta, prompt):
        """Prefill-role admission: pick a decode peer, announce the pair,
        and either run handoff prefill (blocks stream as they seal) or —
        for prompts with no transferable full block — forward the commit
        frame immediately (pure proxy).  Returns False to fall back to
        serving the request locally (no live peer / peer unreachable);
        the published ``{"decode": None}`` pair tells the client so."""
        model = meta.get("model", "")
        peer = self._pick_decode_peer()
        if peer is not None and self._xfer is not None:
            self._xfer.register(req_id, peer, model,
                                self._wire_dtype(model))
            # the expect frame goes out synchronously BEFORE the pair is
            # visible: once a client can learn the pair, the decode half
            # already knows the request (arms its orphan janitor)
            if not self._xfer.send_expect_now(req_id, {
                    "model": model,
                    "prefill_ep": self._advertised_ep(),
                    "deadline_ms": meta.get("deadline_ms")}):
                self._xfer.forget(req_id)
                peer = None
        else:
            peer = None
        self._publish_pair(req_id, peer)
        if peer is None:
            _tm.inc("serving_handoff_fallback_total")
            return False
        prompt_list = [int(t) for t in np.asarray(prompt).reshape(-1)]
        entry = {"decode": peer, "meta": dict(meta),
                 "prompt": prompt_list,
                 "t_arrive": time.perf_counter()}
        with self._pair_lock:
            self._pairs[req_id] = entry
            while len(self._pairs) > _REPLY_RING:
                self._pairs.pop(next(iter(self._pairs)))
        upto = self.decode_engine.handoff_prefill_upto(model,
                                                       len(prompt_list))
        if upto <= 0:
            # nothing transferable below the tail: the commit frame
            # carries the whole prompt and the decode half does all work
            self._xfer.enqueue_commit(req_id, self._commit_meta(
                entry, digests=[],
                phases={"prefill_queue_wait_ms": 0.0, "prefill_ms": 0.0}))
            return True
        tp = meta.get(codec.TRACEPARENT)
        with _tr.remote_parent(tp):
            with _tr.span("serving.admission", req_id=req_id, decode=True,
                          handoff=True, model=model, rank=self.rank):
                self.decode_engine.submit(
                    model, prompt_list,
                    max_new_tokens=int(meta.get("max_new_tokens", 16)),
                    tenant=meta.get("tenant", "default"),
                    deadline_ms=meta.get("deadline_ms"),
                    eos_id=int(meta.get("eos_id", -1)),
                    req_id=req_id, traceparent=tp,
                    tier=meta.get(codec.TIER),
                    handoff=True, callback=self._handoff_done)
        return True

    def _commit_meta(self, entry, digests, phases):
        meta = entry["meta"]
        dl = meta.get("deadline_ms")
        remaining = None
        if dl:
            used = (time.perf_counter() - entry["t_arrive"]) * 1e3
            remaining = max(1.0, float(dl) - used)
        return {"model": meta.get("model", ""), "prompt": entry["prompt"],
                "max_new": int(meta.get("max_new_tokens", 16)),
                "eos_id": int(meta.get("eos_id", -1)),
                "stream": bool(meta.get("stream")),
                "tenant": meta.get("tenant", "default"),
                "tier": meta.get(codec.TIER),
                "deadline_ms": remaining,
                codec.TRACEPARENT: meta.get(codec.TRACEPARENT),
                "digests": list(digests), "phases": dict(phases),
                "sent_unix": time.time(),
                "prefill_ep": self._advertised_ep()}

    def _on_block_sealed(self, m, s, j, digest):
        """Engine hook (step lock held): copy the sealed block's payload
        off the carry and queue the transfer frame."""
        if self._xfer is None:
            return
        try:
            arrays = m.cache.export_block(s.blocks[j])
        except Exception:
            _tm.inc("kv_xfer_send_errors_total")
            return
        self._xfer.enqueue_block(s.pending.req_id, j, digest, arrays)

    def _on_handoff(self, m, s):
        """Engine hook (step lock held): the feed pointer reached the
        boundary — queue the commit frame with prefill-side phases."""
        rid = s.pending.req_id
        with self._pair_lock:
            entry = self._pairs.get(rid)
        if entry is None or self._xfer is None:
            return
        now = time.perf_counter()
        t_admit = s.t_admit if s.t_admit is not None else now
        phases = {
            "prefill_queue_wait_ms": round(
                (t_admit - s.pending.t_submit) * 1e3, 3),
            "prefill_ms": round((now - t_admit) * 1e3, 3),
            "prefill_cached_tokens": s.cached_tokens}
        bs = m.kv_config.block_size
        digests = list(s.hashes[:s.prefill_upto // bs]) if s.hashes else []
        self._xfer.enqueue_commit(rid, self._commit_meta(entry, digests,
                                                         phases))

    def _handoff_done(self, pending):
        """Prefill-side completion callback: "handoff" means the commit
        frame already went out; any other terminal (shed / abort /
        timeout / error) relays a cancel so the decode half frees its
        adoptions and publishes the reply the client is parked on."""
        if pending.reply.status == "handoff":
            return
        self._relay_cancel(pending.req_id, pending.reply.to_meta())

    def _relay_cancel(self, rid, reply_meta):
        with self._pair_lock:
            entry = self._pairs.pop(rid, None)
        if entry is not None and self._xfer is not None:
            self._xfer.enqueue_cancel(rid, reply_meta)

    def _reconcile_abort(self, rid):
        """A client ``__abort__`` frees blocks on BOTH halves: the
        prefill side relays a cancel to its pair's decode half; the
        decode side forgets any uncommitted adoptions."""
        self._relay_cancel(rid, {"status": "aborted",
                                 "error": "aborted by client"})
        if self._adopt is not None:
            entry = self._adopt.cancel(rid)
            if entry is not None and entry["digests"] \
                    and self.decode_engine is not None:
                self.decode_engine.forget_adopted(entry["model"],
                                                  entry["digests"])

    def _tracker(self):
        if self._adopt is None:
            from .disagg import AdoptTracker

            self._adopt = AdoptTracker(self._on_orphan)
        return self._adopt

    def _on_kvxfer(self, req_id, arr):
        if self.decode_engine is None:
            return
        try:
            meta, arrays = codec.unpack_kvxfer(arr)
        except ValueError as e:
            _tm.inc("kv_xfer_rejected_total", reason="frame")
            _tr.note("kvxfer_reject", req_id=req_id, error=str(e)[:200])
            return
        kind = meta.get("kind")
        if kind == "session":
            self._on_session(req_id, meta, arrays)
            return
        if kind == "block" and meta.get("session"):
            self._on_session_block(req_id, meta, arrays)
            return
        tracker = self._tracker()
        if kind == "expect":
            tracker.expect(req_id, meta)
        elif kind == "block":
            err = tracker.on_block(req_id, meta)
            if err is not None:
                _tm.inc("kv_xfer_rejected_total", reason="position")
                _tr.note("kvxfer_reject", req_id=req_id, error=err)
                return
            self.decode_engine.adopt_kv_block(
                meta.get("model", ""), meta["digest"], arrays)
        elif kind == "commit":
            self._on_commit(req_id, meta)
        elif kind == "cancel":
            entry = tracker.cancel(req_id)
            if entry is not None and entry["digests"]:
                self.decode_engine.forget_adopted(entry["model"],
                                                  entry["digests"])
            self._publish_cancel(req_id, meta.get("reply") or {})

    def _on_commit(self, req_id, meta):
        """Commit frame: submit through the ordinary engine path — the
        adopted blocks are found by the admission-time prefix match like
        any warm-cache hit — and merge the prefill-side phases into the
        reply so loadgen can attribute TTFT per role."""
        self._tracker().commit(req_id)
        model = meta.get("model", "")
        stream = bool(meta.get("stream"))
        on_token = self._stream_publisher(req_id) if stream else None
        extra = dict(meta.get("phases") or {})
        sent = meta.get("sent_unix")
        if sent:
            extra["xfer_ms"] = round(
                max(0.0, (time.time() - float(sent)) * 1e3), 3)
        extra["role"] = "disagg"
        tp = meta.get(codec.TRACEPARENT)

        def cb(pending):
            rep = pending.reply
            rep.phases.update(extra)
            self._publish(pending.req_id, rep, pending)

        with _tr.remote_parent(tp):
            with _tr.span("serving.adopt_commit", req_id=req_id,
                          model=model, rank=self.rank):
                self.decode_engine.submit(
                    model, meta.get("prompt") or [],
                    max_new_tokens=int(meta.get("max_new", 16)),
                    tenant=meta.get("tenant", "default"),
                    deadline_ms=meta.get("deadline_ms"),
                    eos_id=int(meta.get("eos_id", -1)),
                    req_id=req_id, traceparent=tp,
                    tier=meta.get("tier"),
                    on_token=on_token, callback=cb)

    def _on_session_block(self, req_id, meta, arrays):
        """Session-migration block frame (``kind=block, session=1``):
        sealed history blocks adopt straight into the pool/prefix index
        — warming it whether or not the resume itself lands — while the
        tail partial block is held host-side until the session frame
        consumes it (a partial block must never be indexed)."""
        if self._resume_buf is None or self.decode_engine is None:
            _tm.inc("kv_migrate_refused_total", reason="disabled")
            return
        if meta.get("tail"):
            self._resume_buf.put_tail(req_id, meta.get("digest"),
                                      meta.get("valid", 0), arrays)
            return
        res = self.decode_engine.adopt_kv_block(
            meta.get("model", ""), meta["digest"], arrays)
        if res == "adopted":
            # only freshly-adopted digests are reconciled on refusal —
            # "cached" blocks belong to earlier traffic, not this hand-off
            self._resume_buf.note_adopted(req_id, meta["digest"])

    def _publish_resume_ack(self, req_id, status, error=None):
        doc = {"status": status}
        if error:
            doc["error"] = error
        key = codec.RESUME_ACK_KEY + req_id
        self.rpc.set_var(key, codec.pack(doc))
        with self._reply_lock:
            self._reply_keys.append(key)
            while len(self._reply_keys) > _REPLY_RING:
                self.rpc.del_var(self._reply_keys.pop(0))

    def _on_session(self, req_id, meta, arrays):
        """Session manifest (sent LAST on the migration FIFO): consume
        the buffered tail, resume through the ordinary submit path
        (``resume_from`` replays already-emitted tokens without
        re-emitting them), and publish the verdict under
        ``__resumeack__`` — the source only finishes its victim as
        "migrated" after reading "resumed" here."""
        entry = (self._resume_buf.take(req_id)
                 if self._resume_buf is not None else None) or {}
        if self._resume_buf is None or self.decode_engine is None:
            _tm.inc("kv_migrate_refused_total", reason="disabled")
            self._publish_resume_ack(req_id, "refused",
                                     "session migration disabled here")
            return
        try:
            prompt = [int(t) for t in np.asarray(arrays[0]).reshape(-1)]
            resume_out = np.asarray(arrays[1]).reshape(-1)
        except Exception:
            _tm.inc("kv_migrate_refused_total", reason="bad_resume")
            self._publish_resume_ack(req_id, "refused",
                                     "malformed session manifest")
            return
        if int(meta.get("pos", -1)) != len(prompt) + len(resume_out) - 1:
            _tm.inc("kv_migrate_refused_total", reason="pos_mismatch")
            self._publish_resume_ack(
                req_id, "refused",
                "manifest pos %s disagrees with prompt+tokens %d"
                % (meta.get("pos"), len(prompt) + len(resume_out) - 1))
            return
        resume_tail = None
        if entry.get("tail") is not None:
            resume_tail = {"digest": entry.get("tail_digest"),
                           "valid": entry.get("tail_valid", 0),
                           "arrays": entry.get("tail")}
        self._resume_submit(req_id, meta, prompt, resume_out, resume_tail,
                            entry.get("digests") or [])

    def _on_resume(self, req_id, arr):
        """Client crash-resume (``__resume__`` frame): prompt + tokens
        the client already holds.  Any replica resumes; warm history
        blocks — earlier traffic or a prior migration — cap re-prefill
        at O(tokens since last sealed block) instead of O(context)."""
        try:
            meta, arrays = codec.unpack(arr)
            prompt = [int(t) for t in np.asarray(arrays[0]).reshape(-1)]
            resume_out = np.asarray(arrays[1]).reshape(-1)
        except Exception:
            _tm.inc("serving_bad_request_total")
            self._publish_resume_ack(req_id, "refused",
                                     "malformed resume request")
            return
        if self.decode_engine is None:
            self._publish_resume_ack(req_id, "refused",
                                     "replica has no decode engine")
            return
        self._resume_submit(req_id, meta, prompt, resume_out, None, [])

    def _resume_submit(self, req_id, meta, prompt, resume_out,
                       resume_tail, adopted_digests):
        """Shared resume admission: submit with ``resume_from`` and ack
        the synchronous verdict.  An admission-time refusal (bad resume
        state, duplicate req_id, draining) reconciles any blocks this
        hand-off adopted so the destination's pool is left exactly as
        found."""
        model = meta.get("model", "")
        on_token = (self._stream_publisher(req_id)
                    if meta.get("stream") else None)
        tp = meta.get(codec.TRACEPARENT)
        with _tr.remote_parent(tp):
            with _tr.span("serving.resume", req_id=req_id, model=model,
                          rank=self.rank):
                pending = self.decode_engine.submit(
                    model, prompt,
                    max_new_tokens=int(meta.get("max_new_tokens", 16)),
                    tenant=meta.get("tenant", "default"),
                    deadline_ms=meta.get("deadline_ms"),
                    eos_id=int(meta.get("eos_id", -1)),
                    req_id=req_id, traceparent=tp,
                    tier=meta.get("tier"),
                    on_token=on_token,
                    resume_from=resume_out, resume_tail=resume_tail,
                    callback=lambda pending: self._publish(
                        pending.req_id, pending.reply, pending))
        rep = getattr(pending, "reply", None)
        if rep is not None and rep.status in ("error", "shed"):
            if adopted_digests:
                self.decode_engine.forget_adopted(model, adopted_digests)
            self._publish_resume_ack(req_id, "refused", rep.error)
            return False
        self._publish_resume_ack(req_id, "resumed")
        return True

    def _on_preempt(self, victims):
        """Engine preemption hook (fires OUTSIDE the engine lock, on the
        decode-loop thread): push each preempted-youngest session to the
        least-loaded peer on a side thread — the destination-ack wait
        must never block the step loop.  A refused or failed push just
        leaves the victim queued for local deterministic recompute."""
        mig = self.migrator
        if mig is None or not victims:
            return

        def push():
            for rid, model in victims:
                del model
                try:
                    mig.migrate(rid, trigger="pressure")
                except ValueError:
                    pass           # already finished/recomputed: fine

        threading.Thread(target=push, name="serving-migrate-pressure",
                         daemon=True).start()

    def _publish_cancel(self, req_id, reply_meta):
        from .engine import InferReply

        status = reply_meta.get("status") or "aborted"
        if status in ("ok", "handoff"):
            status = "error"
        rep = InferReply(status, error=reply_meta.get("error"),
                         retry_after_ms=reply_meta.get("retry_after_ms")
                         or 0.0)
        # unblock a parked streaming client, then publish the reply
        self._stream_publisher(req_id)(req_id, 0, None, True, rep.status)
        self._publish(req_id, rep)

    def _on_orphan(self, rid, entry):
        """Janitor verdict: the prefill half died before committing this
        request.  Free the adopted blocks and publish a timeout so the
        client's ordinary replay path takes over — no admitted request is
        ever dropped by a prefill SIGKILL."""
        from .engine import InferReply

        if entry.get("digests") and self.decode_engine is not None:
            self.decode_engine.forget_adopted(entry.get("model") or "",
                                              entry["digests"])
        _tr.note("kvxfer_orphan", req_id=rid)
        self._stream_publisher(rid)(rid, 0, None, True, "timeout")
        self._publish(rid, InferReply(
            "timeout",
            error="prefill half died before handoff commit"))

    def _stream_publisher(self, req_id):
        """Per-token chunk publisher: ``__stream__:<id>:<k>`` carries the
        k-th generated token; the final/terminal chunk sets done.  Chunk
        keys join the reply GC ring so crashed streamers can't leak."""

        def on_token(rid, index, token, done, status):
            key = "%s%s:%d" % (codec.STREAM_KEY, rid, index)
            self.rpc.set_var(key, codec.pack(
                {"i": int(index), "done": bool(done), "status": status,
                 "token": None if token is None else int(token)}))
            with self._reply_lock:
                self._reply_keys.append(key)
                while len(self._reply_keys) > _REPLY_RING:
                    self.rpc.del_var(self._reply_keys.pop(0))
        return on_token

    def _publish(self, req_id, reply, pending=None):
        from .engine import InferReply

        fault = maybe_fail("serving.reply")
        if fault == "drop":
            return                     # reply lost: client GET times out
        if reply is None:
            reply = InferReply("error", error="malformed request")
        if fault == "error":
            reply = InferReply("error",
                               error="injected fault: serving.reply")
        # runs inside _Pending.complete(), so parent explicitly under the
        # request span rather than whatever is on the completing thread
        with _tr.span("serving.reply_publish",
                      parent=getattr(pending, "span", None),
                      req_id=req_id, status=reply.status):
            meta = reply.to_meta()
            tp = getattr(pending, "traceparent", None)
            if tp:
                meta[codec.TRACEPARENT] = tp
            names = list(reply.outputs)
            buf = codec.pack(meta, [reply.outputs[n] for n in names])
            key = codec.REPLY_KEY + req_id
            self.rpc.set_var(key, buf)
        with self._reply_lock:
            self._reply_keys.append(key)
            while len(self._reply_keys) > _REPLY_RING:
                self.rpc.del_var(self._reply_keys.pop(0))

    # -- control plane -------------------------------------------------------

    def apply_rollout(self, doc):
        """Adopt a version-routing state (local command or coordinator
        ``__rollout_set__`` broadcast) and republish this replica's view
        under ``__rollout__`` — the chaos leg asserts every survivor
        converges to the same doc."""
        self.engine.apply_routes(doc.get("models") or {})
        self.rpc.set_var(codec.ROLLOUT_KEY,
                         codec.pack({"models": self.engine.routes()}))

    def _on_rollout_set(self, arr):
        try:
            doc, _ = codec.unpack(arr)
        except Exception:
            _tm.inc("serving_bad_request_total")
            return
        self.apply_rollout(doc)

    def _on_rollout_ctl(self, req_id, arr):
        from .engine import InferReply

        try:
            cmd, _ = codec.unpack(arr)
        except Exception:
            self._publish(req_id, None)
            _tm.inc("serving_bad_request_total")
            return
        if self.rollout is None:
            reply = InferReply("error",
                               error="replica has no rollout controller")
        else:
            meta = self.rollout.handle(cmd)
            reply = InferReply(meta.get("status", "error"),
                               error=meta.get("error"))
            reply.phases = {k: v for k, v in meta.items()
                            if k not in ("status", "error")}
        self._publish(req_id, reply)

    def _on_retire(self):
        """Drain both engines at a batch boundary on a side thread (the
        poll loop must keep serving queued work), then fire on_retire."""
        if self._retire_thread is not None:
            return

        def drain():
            from .. import flags

            self.engine.drain()
            if self.decode_engine is not None:
                mig = None
                if self.migrator is not None \
                        and flags.flag("migrate_on_drain"):
                    mig = self.migrator.drain_push(trigger="drain")
                self.decode_engine.drain(migrate=mig)
            _tm.event("serving_retired", rank=self.rank)
            if self.on_retire is not None:
                self.on_retire()

        self._retire_thread = threading.Thread(
            target=drain, name="serving-retire", daemon=True)
        self._retire_thread.start()

    def set_alive(self, epoch, is_coordinator):
        self.rpc.set_var(codec.ALIVE_KEY, np.asarray(
            [self.rank, int(epoch), 1 if is_coordinator else 0], np.int64))

    def shutdown(self):
        if self._stopped.is_set():
            return
        self._stopped.set()
        if self._pub_stop is not None:
            # stop AND join (idempotent) — a leaked publisher thread
            # would republish __metrics__ into the next test's server
            self._pub_stop.stop()
        if self.rollout is not None:
            self.rollout.stop()
        if self.fleet is not None:
            self.fleet.stop()
        self.engine.stop()
        if self.decode_engine is not None:
            self.decode_engine.stop()
        if self._xfer is not None:
            self._xfer.close()
        if self._adopt is not None:
            self._adopt.close()
        if self.migrator is not None:
            self.migrator.close()
        self.rpc.shutdown()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
