"""RPC serving frontend: wire protocol over native/rpc.py.

One ``RpcServer`` per replica carries the whole protocol:

  ``__infer__:<req_id>``  inbound SEND: packed request (serving/codec.py);
                          the reply is published as ``__reply__:<req_id>``
                          and the client's blocking GET picks it up (the
                          transport parks GETs until the var exists)
  ``__alive__``           [rank, epoch, is_coordinator] — same probe
                          contract as the elastic control plane
  ``__metrics__``         telemetry snapshot, republished every second
                          (core/telemetry.start_publisher) for
                          tools/metrics_dump.py --scrape
  ``__spec__:<model>``    feed/fetch signature + buckets, so loadgen can
                          synthesize valid requests without the model dir
  ``__fhb__<rank>``       fleet replica heartbeats (serving/fleet.py)

Replies are garbage-collected FIFO beyond a bounded ring — a crashed
client can never grow the server's var store unboundedly.
"""

import threading

import numpy as np

from ..core import telemetry as _tm
from ..core import tracing as _tr
from ..native.rpc import EV_SEND, RpcServer
from . import codec

__all__ = ["ServingServer"]

_REPLY_RING = 1024


class ServingServer:
    def __init__(self, engine, port=0, rank=0):
        self.engine = engine
        self.rank = int(rank)
        self.rpc = RpcServer(port=port)
        self.port = self.rpc.port
        self.fleet = None
        self._reply_keys = []
        self._reply_lock = threading.Lock()
        self._thread = None
        self._pub_stop = None
        self._stopped = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self.engine.start()
        self.rpc.set_var(codec.ALIVE_KEY,
                         np.asarray([self.rank, 0, 0], np.int64))
        for name in self.engine.models():
            self.rpc.set_var(codec.SPEC_KEY + name,
                             codec.pack(self.engine.spec(name)))
        self.rpc.serve(True)
        if _tm.enabled():
            self._pub_stop = _tm.start_publisher(self.rpc, interval_s=1.0)
        self._thread = threading.Thread(target=self._poll_loop,
                                        name="serving-rpc", daemon=True)
        self._thread.start()
        return self

    def attach_fleet(self, fleet):
        """Wire a serving fleet: its heartbeats arrive on this server's
        event stream, and membership changes publish at batch boundaries
        via the engine hook."""
        self.fleet = fleet
        self.engine.on_batch_boundary = fleet.tick

    def _poll_loop(self):
        while True:
            t, name, arr = self.rpc.poll()
            if t == 0:
                return
            if t != EV_SEND or name is None:
                continue
            if name.startswith(codec.INFER_KEY):
                self._on_infer(name[len(codec.INFER_KEY):], arr)
            elif self.fleet is not None:
                self.fleet.on_event(name, arr)
            if self.fleet is not None:
                self.fleet.tick()

    def _on_infer(self, req_id, arr):
        try:
            meta, arrays = codec.unpack(arr)
            feeds = dict(zip(meta["feeds"], arrays))
        except Exception as e:
            self._publish(req_id, None)
            _tm.inc("serving_bad_request_total")
            del e
            return
        tp = meta.get(codec.TRACEPARENT)
        # the admission span parents under the client's root span (wire
        # context), and engine.submit opens the request span inside it
        with _tr.remote_parent(tp):
            with _tr.span("serving.admission", req_id=req_id,
                          model=meta.get("model", ""), rank=self.rank):
                self.engine.submit(
                    meta.get("model", ""), feeds,
                    tenant=meta.get("tenant", "default"),
                    deadline_ms=meta.get("deadline_ms"),
                    req_id=req_id,
                    traceparent=tp,
                    callback=lambda pending: self._publish(
                        pending.req_id, pending.reply, pending))

    def _publish(self, req_id, reply, pending=None):
        from .engine import InferReply

        if reply is None:
            reply = InferReply("error", error="malformed request")
        # runs inside _Pending.complete(), so parent explicitly under the
        # request span rather than whatever is on the completing thread
        with _tr.span("serving.reply_publish",
                      parent=getattr(pending, "span", None),
                      req_id=req_id, status=reply.status):
            meta = reply.to_meta()
            tp = getattr(pending, "traceparent", None)
            if tp:
                meta[codec.TRACEPARENT] = tp
            names = list(reply.outputs)
            buf = codec.pack(meta, [reply.outputs[n] for n in names])
            key = codec.REPLY_KEY + req_id
            self.rpc.set_var(key, buf)
        with self._reply_lock:
            self._reply_keys.append(key)
            while len(self._reply_keys) > _REPLY_RING:
                self.rpc.del_var(self._reply_keys.pop(0))

    def set_alive(self, epoch, is_coordinator):
        self.rpc.set_var(codec.ALIVE_KEY, np.asarray(
            [self.rank, int(epoch), 1 if is_coordinator else 0], np.int64))

    def shutdown(self):
        if self._stopped.is_set():
            return
        self._stopped.set()
        if self._pub_stop is not None:
            # stop AND join (idempotent) — a leaked publisher thread
            # would republish __metrics__ into the next test's server
            self._pub_stop.stop()
        if self.fleet is not None:
            self.fleet.stop()
        self.engine.stop()
        self.rpc.shutdown()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
