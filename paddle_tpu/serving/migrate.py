"""Live decode-session migration: in-flight generations survive replica
death, drain, and rollout without re-prefill.

A decoding sequence is, at any iteration boundary, fully described by a
small **manifest** (prompt, emitted tokens, feed position p, decode
params) plus the KV state for positions ``[0, p)`` — and that KV state
is already content-addressed: the engine extends each sequence's prefix
hash chain over *generated* tokens as decode crosses block boundaries
(``h_i = sha(h_{i-1}, block_tokens)`` over prompt ++ out), publishing
every completed history block into the local prefix index exactly like a
prompt block.  Migration is therefore a transfer of (manifest, sealed
blocks the destination does not hold, one tail partial block), and
resume is an ordinary admission that prefix-matches the full-history
chain instead of just the prompt — greedy decode makes the continuation
bitwise identical to an uninterrupted run.

Wire format (one FIFO ``__kvxfer__:<req_id>`` stream, send order =
arrival order):

  block frames      ``kind=block, session=1`` — one per sealed history
                    block not recently shipped to this peer, adopted on
                    arrival via ``DecodeEngine.adopt_kv_block`` (alloc,
                    install, publish digest, park evictable: the
                    destination's prefix index stays warm even if the
                    resume itself is later refused)
  tail frame        ``kind=block, session=1, tail=1, valid=n`` — the
                    partial block holding positions past the last sealed
                    boundary, "sealed at migration time" under a
                    domain-separated digest (``tail_digest``) that can
                    never collide with a chain digest; held host-side by
                    the destination's ``ResumeBuffer`` until the
                    manifest lands, then installed into a private block
                    owned by the resumed sequence (never indexed — a
                    partial block must not prefix-match)
  session frame     ``kind=session`` — the manifest, sent LAST; arrays
                    [prompt, emitted tokens], meta carries position,
                    chain digests, decode params and remaining deadline.
                    The destination resumes and publishes its verdict
                    under ``__resumeack__:<req_id>``.

Trigger matrix:

  crash     the victim is gone; the client re-submits ``__resume__``
            with the tokens it already holds, and any replica with
            matching history blocks (warmed by earlier traffic or a
            prior migration) skips straight to the tail — recovery is
            O(tokens since last sealed block), not O(context)
  drain     ``DecodeEngine.drain(migrate=...)``: a retiring replica
            (autoscale-down, rollout flip) pushes live sessions to
            peers at a batch boundary instead of waiting out long
            generations
  pressure  a preempted-youngest sequence may be pushed to the
            least-loaded peer (fleetmon occupancy) instead of waiting
            for local deterministic recompute

Reconciliation rules (no token is ever emitted twice, no session is
ever dropped OR double-run):

- the source parks the victim outside the active set for the whole
  hand-off (``export_session``); only after the destination acks
  "resumed" does it free the blocks and finish the victim with status
  "migrated" (reply phases carry ``migrated_to`` so the client follows).
  Any failure — send error, ack timeout, destination refusal — aborts
  the hand-off and re-queues the victim locally for deterministic
  recompute: at most one replica ever runs the session.
- the destination refuses a resume for a req_id it already has live
  (loud double-migration refusal), refuses manifests whose position
  disagrees with prompt+tokens, and refuses sessions still in prefill
  at the source (those re-prefill cheaply anyway).
- a resumed sequence starts emitting at token index len(tokens): the
  client's index-dedupe (``generate_stream``) makes re-delivery
  impossible even when a slow victim raced a few extra chunks out.

Telemetry: ``kv_migrate_sessions_total{trigger,model}``,
``kv_migrate_blocks_total`` / ``kv_migrate_bytes_total{dtype}``,
``kv_migrate_failed_total{trigger}``, ``kv_migrate_refused_total
{reason}``, ``kv_migrate_resume_total{result}``, and the end-to-end
``migration_ms`` histogram (export -> destination ack).
"""

import hashlib
import threading
import time
from collections import OrderedDict

from .. import flags as _flags
from ..core import telemetry as _tm
from ..core import tracing as _tr
from ..native.rpc import RpcClient
from . import codec

__all__ = ["SessionMigrator", "ResumeBuffer", "tail_digest"]

# machine-readable concurrency contracts (tools/threadlint.py):
# the migrator's lock is a LEAF guarding only in-memory maps (shipped
# LRUs, the closed flag) — all RPC happens strictly outside it on a
# dedicated per-hand-off connection, engine calls (export/commit/abort
# acquire DecodeEngine._cond) happen outside it too, and peer discovery
# callbacks fire unlocked
LOCK_ORDER = (
    ("DecodeEngine._cond", "SessionMigrator._lock"),
)
UNLOCKED_CALLBACKS = (
    "SessionMigrator.peers_fn",
)

# per-peer recently-shipped digest LRU (same role as the disagg
# sender's): a peer warmed by earlier migrations or disagg streaming
# skips the wire for blocks it already indexed
_SHIPPED_CAP = 4096
# destination-side tail payloads older than this are purged — the
# manifest frame follows its tail on the same FIFO connection, so a gap
# this long means the source died mid-hand-off
_RESUME_BUF_TTL_S = 60.0


def tail_digest(prev_hex, token_ids):
    """Transfer label for a tail partial block sealed at migration time.

    Chains off the last full block's digest like a real chain step but
    under a separate domain (the ``#tail`` suffix), so it can never
    collide with — or be matched as — a full-block chain digest."""
    h = (bytes.fromhex(prev_hex) if prev_hex
         else hashlib.sha256(b"kvtail:").digest())
    d = hashlib.sha256(h)
    d.update(b"".join(int(t).to_bytes(8, "little", signed=True)
                      for t in token_ids))
    d.update(b"#tail")
    return d.hexdigest()


class ResumeBuffer:
    """Destination-side holding area for in-flight session hand-offs.

    A migration's tail frame precedes its manifest on the wire; the
    buffer keeps the tail payload (host arrays — one block, a few KB at
    smoke scale) keyed by req_id until the session frame consumes it.
    Entries also remember adopted chain digests so a refused resume can
    be reconciled (the server forgets them, truly freeing still-evictable
    blocks).  Stale entries are purged lazily on every touch."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}      # req_id -> dict

    def _entry_locked(self, req_id):
        e = self._entries.get(req_id)
        if e is None:
            e = self._entries[req_id] = {
                "tail": None, "tail_valid": 0, "tail_digest": None,
                "digests": [], "t0": time.monotonic()}
        return e

    def _purge_locked(self, now):
        dead = [rid for rid, e in self._entries.items()
                if now - e["t0"] > _RESUME_BUF_TTL_S]
        for rid in dead:
            del self._entries[rid]
            _tm.inc("kv_migrate_refused_total", reason="stale_buffer")

    def note_adopted(self, req_id, digest):
        with self._lock:
            self._purge_locked(time.monotonic())
            self._entry_locked(req_id)["digests"].append(digest)

    def put_tail(self, req_id, digest, valid, arrays):
        with self._lock:
            self._purge_locked(time.monotonic())
            e = self._entry_locked(req_id)
            e["tail"] = list(arrays)
            e["tail_valid"] = int(valid)
            e["tail_digest"] = digest

    def take(self, req_id):
        """Consume and return the buffered entry (None when the
        migration never shipped blocks — e.g. a pressure-trigger
        hand-off whose tail was recomputed-away)."""
        with self._lock:
            return self._entries.pop(req_id, None)

    def __len__(self):
        with self._lock:
            return len(self._entries)


class SessionMigrator:
    """Source-side session-migration manager.

    Orchestrates the three-phase hand-off around the engine's
    snapshot/commit/abort primitives:

      1. ``engine.export_session(req_id)`` detaches the sequence at an
         iteration boundary and snapshots manifest + block payloads
         (host copies) — the engine keeps it parked, invisible to the
         scheduler, until phase 3 decides its fate;
      2. frames stream to the peer on this process's one migration
         connection per peer (FIFO: blocks, tail, then the manifest),
         and the destination's ``__resumeack__`` verdict is awaited;
      3. "resumed" -> ``commit_migration`` (free blocks, finish the
         victim with status "migrated" + ``migrated_to``); anything
         else -> ``abort_migration`` (re-queue locally, zero drops).

    ``peers_fn`` (fired unlocked) supplies candidate endpoints;
    ``occupancy_fn`` (optional, fleetmon-backed) maps endpoint ->
    windowed KV occupancy so ``pick_peer`` prefers the least-loaded
    survivor."""

    def __init__(self, engine, peers_fn=None, occupancy_fn=None):
        self.engine = engine
        self.peers_fn = peers_fn or (lambda: [])
        self.occupancy_fn = occupancy_fn
        self._lock = threading.Lock()
        self._shipped = {}              # endpoint -> OrderedDict(digest)
        self._closed = False

    # -- peer selection ------------------------------------------------------

    def pick_peer(self, exclude=()):
        """Least-loaded live candidate, or None when alone."""
        try:
            peers = [p for p in (self.peers_fn() or []) if p not in exclude]
        except Exception:
            peers = []
        if not peers:
            return None
        if self.occupancy_fn is not None:
            try:
                occ = self.occupancy_fn()
                peers.sort(key=lambda p: occ.get(p, 0.5))
            except Exception:
                pass
        return peers[0]

    # -- hand-off ------------------------------------------------------------

    def migrate(self, req_id, peer=None, trigger="drain"):
        """Push one live session to ``peer`` (auto-picked when None).
        True only when the destination acked "resumed" and the victim
        was committed away; on ANY other outcome the session is back in
        the local scheduler (or was never detached) and False returns.
        Raises ValueError for loud refusals (unknown/in-prefill/double
        migration) — the engine has not been perturbed in that case."""
        if peer is None:
            peer = self.pick_peer()
        if peer is None:
            return False
        t0 = time.perf_counter()
        manifest, payloads = self.engine.export_session(req_id)
        ok = False
        try:
            ok = self._push(peer, manifest, payloads)
        finally:
            # commit/abort exactly once, even if _push raised
            if ok:
                self.engine.commit_migration(req_id, peer)
                _tm.inc("kv_migrate_sessions_total", trigger=trigger,
                        model=manifest["model"])
                _tm.observe("migration_ms",
                            (time.perf_counter() - t0) * 1000.0)
                _tr.note("migrate", req_id=req_id, peer=peer,
                         trigger=trigger, pos=manifest["pos"])
            else:
                self.engine.abort_migration(req_id)
                _tm.inc("kv_migrate_failed_total", trigger=trigger)
        return ok

    def drain_push(self, trigger="drain"):
        """Callback for ``DecodeEngine.drain(migrate=...)``: each live
        session gets its own (least-loaded) peer pick; refusals read as
        False so drain falls back to waiting that session out."""
        def push(req_id, model):
            del model
            try:
                return self.migrate(req_id, trigger=trigger)
            except ValueError:
                return False
        return push

    # -- wire ----------------------------------------------------------------

    def _skip_shipped(self, peer, digest):
        """True when ``digest`` was recently shipped to ``peer`` (LRU
        touch).  A racing concurrent hand-off may ship a digest twice —
        the destination's adopt answers "cached", which is harmless."""
        with self._lock:
            shipped = self._shipped.setdefault(peer, OrderedDict())
            if digest in shipped:
                shipped.move_to_end(digest)
                return True
        return False

    def _mark_shipped(self, peer, digest):
        with self._lock:
            shipped = self._shipped.setdefault(peer, OrderedDict())
            shipped[digest] = True
            while len(shipped) > _SHIPPED_CAP:
                shipped.popitem(last=False)

    def _push(self, peer, manifest, payloads):
        """Stream blocks + tail + manifest, then await the ack — all on
        one DEDICATED connection, so the frame order the destination
        sees is trivially FIFO without holding any lock across the wire
        (the engine's export already guarantees at most one in-flight
        hand-off per session)."""
        rid = manifest["req_id"]
        model = manifest["model"]
        dtype = manifest.get("dtype", "f32")
        # token arrays ride the session frame's payload, not its JSON meta
        p_arr = manifest.pop("_prompt_arr")
        o_arr = manifest.pop("_out_arr")
        with self._lock:
            if self._closed:
                return False
        ack_s = float(_flags.flag("migrate_ack_timeout") or 10.0)
        try:
            cli = RpcClient(peer, connect_timeout=2.0,
                            rpc_deadline=max(ack_s, 5.0), retry_times=0)
        except Exception:
            return False
        try:
            for pos, digest, arrays, is_tail in payloads:
                if not is_tail and self._skip_shipped(peer, digest):
                    _tm.inc("kv_migrate_skipped_total", dtype=dtype)
                    continue
                meta = {"kind": "block", "req_id": rid,
                        "pos": int(pos), "digest": digest,
                        "model": model, "dtype": dtype, "session": 1}
                if is_tail:
                    meta["tail"] = 1
                    meta["valid"] = int(manifest["pos"]
                                        - pos * manifest["block_size"])
                frame = codec.pack_kvxfer(meta, arrays)
                _tr.note("kvxfer", frame_kind="session-block",
                         req_id=rid, peer=peer, pos=int(pos),
                         digest=digest[:16])
                cli.send_var(codec.KVXFER_KEY + rid, frame)
                if not is_tail:
                    self._mark_shipped(peer, digest)
                _tm.inc("kv_migrate_blocks_total", dtype=dtype)
                _tm.inc("kv_migrate_bytes_total", int(frame.nbytes),
                        dtype=dtype)
            sframe = codec.pack_kvxfer(
                dict(manifest, kind="session"), [p_arr, o_arr])
            _tr.note("kvxfer", frame_kind="session", req_id=rid,
                     peer=peer, pos=int(manifest["pos"]), digest="")
            cli.send_var(codec.KVXFER_KEY + rid, sframe)
            ack = cli.get_var(codec.RESUME_ACK_KEY + rid)
        except Exception:
            return False
        finally:
            try:
                cli.close()
            except Exception:
                pass
        try:
            meta, _ = codec.unpack(ack)
        except Exception:
            return False
        return meta.get("status") == "resumed"

    def close(self):
        """Refuse new hand-offs; in-flight pushes finish on their own
        bounded (rpc_deadline) connections."""
        with self._lock:
            self._closed = True
