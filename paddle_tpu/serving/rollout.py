"""Metrics-gated canary rollout for versioned serving models (PR 16).

A new model version is registered BESIDE the old one (``fc@v2`` next to
``fc``) and prewarmed through the same compile cache, so the flip is a
routing change, not a restart.  The coordinator owns the rollout state
machine per base name:

  stable -> canary (``start``: a hash-deterministic traffic fraction
            lands on the new version; engine.resolve does the split)
         -> flipped (``flip``: 100% on the new version)
         -> rolled_back (``abort``, or the metrics GATE tripping)

The gate compares the canary version's scraped stats against the active
version across every live replica: p99 of ``serving_execute_ms{model}``
and error rate from ``serving_request_errors_total{model}`` /
``serving_requests_total{model}``.  ``evaluate_gate`` is pure (unit
tests seed it directly); the controller's monitor thread feeds it live
scrapes and rolls back automatically on a trip.

Consistency: every state change is applied locally, broadcast to live
peers as a ``__rollout_set__`` SEND (idempotent — applied via
``engine.apply_routes``), and published in the epoch-bumped endpoints
file.  The monitor RE-broadcasts periodically, so a replica that missed
a flip (relaunched, or the SEND raced its death) converges within a
re-broadcast interval — the chaos leg SIGKILLs a replica mid-flip and
asserts exactly this.
"""

import logging
import threading
import uuid

from ..core import telemetry as _tm
from ..native import rpc as _rpc
from . import codec

__all__ = ["RolloutController", "evaluate_gate", "stats_from_snapshot",
           "merge_stats"]


def _flag(name):
    from .. import flags

    return flags.flag(name)


# -- gate (pure, unit-testable) ----------------------------------------------

def stats_from_snapshot(snap, model):
    """Per-version stats out of one replica's telemetry snapshot:
    {count, errors, p99_ms} for ``model`` (a version name, e.g. fc@v2)."""
    hist = (snap.get("histograms") or {}).get(
        "serving_execute_ms{model=%s}" % model) or {}
    counters = snap.get("counters") or {}
    errors = 0.0
    requests = 0.0
    for flat, v in counters.items():
        if flat.startswith("serving_request_errors_total{") \
                and "model=%s" % model in flat:
            errors += v
        elif flat.startswith("serving_requests_total{") \
                and "model=%s," % model in flat:
            requests += v
    out = {"count": float(hist.get("count", 0.0)) + errors,
           "requests": requests,
           "errors": errors,
           "p99_ms": float(hist.get("p99", 0.0))}
    # cumulative bucket vector (PR 18 snapshots carry one per histogram)
    # rides along so merge_stats can compute a fleet-EXACT p99 instead
    # of the worst-replica upper bound; absent on pre-18 snapshots
    if hist.get("buckets"):
        out["buckets"] = list(hist["buckets"])
    return out


def merge_stats(per_replica):
    """Fold per-replica stats: counts/errors sum.  When every replica
    shipped a cumulative bucket vector the merged p99 is computed from
    the summed buckets — exact to within one bucket width across the
    whole fleet.  Any bucket-less entry (old replica mid-rollout) drops
    the merge back to the conservative fallback: p99 takes the WORST
    replica (a canary that is slow anywhere trips)."""
    out = {"count": 0.0, "requests": 0.0, "errors": 0.0, "p99_ms": 0.0}
    merged_buckets = None
    exact = True
    for s in per_replica:
        out["count"] += s.get("count", 0.0)
        out["requests"] += s.get("requests", 0.0)
        out["errors"] += s.get("errors", 0.0)
        out["p99_ms"] = max(out["p99_ms"], s.get("p99_ms", 0.0))
        b = s.get("buckets")
        if not b:
            exact = False
            continue
        deltas = _tm.cumulative_to_deltas(b)
        if merged_buckets is None:
            merged_buckets = deltas
        else:
            merged_buckets = [a + d for a, d in zip(merged_buckets, deltas)]
    if exact and merged_buckets is not None and sum(merged_buckets) > 0:
        cum, run = [], 0
        for d in merged_buckets:
            run += d
            cum.append(run)
        out["p99_ms"] = _tm.bucket_percentile(cum, 0.99)
        out["buckets"] = cum
    return out


def evaluate_gate(canary, baseline, p99_ratio=None, error_rate=None,
                  min_samples=None):
    """Canary-vs-active verdict: {"verdict": pass|trip|insufficient,
    "reason": ...}.  Trips when the canary's error rate exceeds
    ``error_rate`` or its p99 exceeds ``p99_ratio`` x the active
    version's; below ``min_samples`` observed canary requests the gate
    abstains (a two-request blip must not roll back a fleet)."""
    p99_ratio = float(p99_ratio if p99_ratio is not None
                      else _flag("rollout_gate_p99_ratio"))
    error_rate = float(error_rate if error_rate is not None
                       else _flag("rollout_gate_error_rate"))
    min_samples = int(min_samples if min_samples is not None
                      else _flag("rollout_gate_min_samples"))
    seen = max(canary.get("count", 0.0), canary.get("requests", 0.0))
    if seen < min_samples:
        return {"verdict": "insufficient",
                "reason": "%d/%d canary samples" % (seen, min_samples)}
    denom = max(canary.get("requests", 0.0), canary.get("count", 0.0), 1.0)
    rate = canary.get("errors", 0.0) / denom
    if rate > error_rate:
        return {"verdict": "trip",
                "reason": "error rate %.3f > %.3f" % (rate, error_rate)}
    base_p99 = baseline.get("p99_ms", 0.0)
    if base_p99 > 0.0 and canary.get("p99_ms", 0.0) > p99_ratio * base_p99:
        return {"verdict": "trip",
                "reason": "p99 %.1fms > %.1fx baseline %.1fms"
                % (canary["p99_ms"], p99_ratio, base_p99)}
    return {"verdict": "pass",
            "reason": "error rate %.3f, p99 %.1fms vs baseline %.1fms"
            % (rate, canary.get("p99_ms", 0.0), base_p99)}


# -- controller --------------------------------------------------------------

class RolloutController:
    """Coordinator-side rollout state machine + gate monitor.

    ``handle`` serves the ``__rollout_ctl__`` admin commands (start /
    flip / abort / status); every mutation applies locally, broadcasts
    ``__rollout_set__`` to live peers, and publishes through the fleet's
    epoch-bumped endpoints file.  The monitor thread re-broadcasts (so
    missed flips converge) and auto-rolls-back a canary whose gate
    trips.  ``scrape_fn`` / ``snapshot_fn`` are injectable for tests."""

    def __init__(self, server, fleet=None, interval_s=0.5,
                 scrape_fn=None, snapshot_fn=None):
        self.server = server
        self.fleet = fleet
        self.interval_s = float(interval_s)
        self._scrape = scrape_fn or (lambda ep: _tm.scrape(ep, timeout=3.0))
        self._snapshot = snapshot_fn or _tm.snapshot
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self.gate_verdicts = {}        # base -> last evaluate_gate result

    @property
    def engine(self):
        return self.server.engine

    def _is_coordinator(self):
        return self.fleet is None or self.fleet.is_coordinator()

    # -- admin commands ------------------------------------------------------

    def handle(self, cmd):
        """One admin command dict -> reply meta dict."""
        op = cmd.get("op")
        if not self._is_coordinator():
            return {"status": "error", "error": "not coordinator"}
        try:
            with self._lock:
                if op == "start":
                    base = cmd["model"]
                    fraction = float(
                        cmd.get("fraction")
                        or _flag("serving_canary_fraction"))
                    self.engine.set_route(
                        base, active=cmd["active"], canary=cmd["canary"],
                        fraction=fraction, state="canary")
                elif op == "flip":
                    base = cmd["model"]
                    route = self.engine.routes().get(base)
                    if route is None or not route.get("canary"):
                        raise ValueError("no canary staged for %r" % base)
                    self.engine.set_route(
                        base, active=route["canary"], canary=None,
                        fraction=0.0, state="flipped")
                elif op == "abort":
                    base = cmd["model"]
                    route = self.engine.routes().get(base)
                    if route is None:
                        raise ValueError("no rollout for %r" % base)
                    self.engine.set_route(
                        base, active=route["active"], canary=None,
                        fraction=0.0, state="rolled_back")
                elif op == "status":
                    return {"status": "ok",
                            "routes": self.engine.routes(),
                            "gates": dict(self.gate_verdicts)}
                else:
                    raise ValueError("unknown rollout op %r" % op)
        except (KeyError, ValueError) as e:
            return {"status": "error", "error": str(e)}
        _tm.event("rollout_" + op, model=cmd.get("model"),
                  routes=self.engine.routes())
        self.broadcast()
        return {"status": "ok", "routes": self.engine.routes()}

    # -- propagation ---------------------------------------------------------

    def broadcast(self):
        """Apply-locally + push ``__rollout_set__`` to every live peer +
        publish through the fleet (endpoints file, epoch bump).
        Idempotent — also the periodic convergence path."""
        doc = {"models": self.engine.routes()}
        self.server.apply_rollout(doc)
        if self.fleet is None:
            return
        buf = codec.pack(doc)
        for r in sorted(self.fleet.live):
            if r == self.fleet.rank:
                continue
            try:
                c = _rpc.RpcClient(self.fleet.endpoints[r],
                                   connect_timeout=1.0, rpc_deadline=3.0,
                                   retry_times=0)
                try:
                    c.send_var(codec.ROLLOUT_SET_KEY, buf)
                finally:
                    c.close()
            except Exception:
                pass  # dead peer: eviction + re-broadcast converge it
        self.fleet.publish_rollout(doc)

    # -- gate monitor --------------------------------------------------------

    def _gather(self, version):
        """Per-version stats folded across self + live peers."""
        per = [stats_from_snapshot(self._snapshot(), version)]
        if self.fleet is not None:
            for r in sorted(self.fleet.live):
                if r == self.fleet.rank:
                    continue
                try:
                    per.append(stats_from_snapshot(
                        self._scrape(self.fleet.endpoints[r]), version))
                except Exception:
                    continue
        return merge_stats(per)

    def check_gates(self):
        """One monitor pass: evaluate every live canary, roll back on a
        trip.  Returns {base: verdict dict} (tests call it directly)."""
        out = {}
        for base, route in self.engine.routes().items():
            if route.get("state") != "canary" or not route.get("canary"):
                continue
            verdict = evaluate_gate(self._gather(route["canary"]),
                                    self._gather(route["active"]))
            out[base] = self.gate_verdicts[base] = verdict
            if verdict["verdict"] == "trip":
                logging.warning("[rollout] gate TRIPPED for %s: %s — "
                                "rolling back", base, verdict["reason"])
                _tm.inc("rollout_rollbacks_total", model=base)
                _tm.event("rollout_rollback", model=base,
                          reason=verdict["reason"])
                with self._lock:
                    self.engine.set_route(
                        base, active=route["active"], canary=None,
                        fraction=0.0, state="rolled_back")
                self.broadcast()
        return out

    def _monitor(self):
        while not self._stop.wait(self.interval_s):
            if not self._is_coordinator():
                continue
            try:
                self.check_gates()
                if self.engine.routes():
                    self.broadcast()   # convergence re-broadcast
            except Exception:
                logging.exception("[rollout] monitor pass failed")

    def start(self):
        self._thread = threading.Thread(target=self._monitor,
                                        name="rollout-monitor", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
