"""Locate the (single) distributed lookup table in a Program.

Parity: python/paddle/fluid/distribute_lookup_table.py — used by the
DistributeTranspiler and fleet PS paths to find the large-scale sparse
embedding table marked ``is_distributed=True``."""

LOOKUP_TABLE_TYPE = "lookup_table"

__all__ = [
    "find_distributed_lookup_table",
    "find_distributed_lookup_table_inputs",
    "find_distributed_lookup_table_outputs",
]


def _table_ops(program, table_name):
    for op in program.global_block().ops:
        if op.type == LOOKUP_TABLE_TYPE and table_name == op.input("W")[0]:
            yield op


def find_distributed_lookup_table_inputs(program, table_name):
    """All Ids variables feeding lookup_table ops over ``table_name``."""
    local_vars = program.current_block().vars
    inputs = []
    for op in _table_ops(program, table_name):
        inputs.extend(local_vars[name] for name in op.input("Ids"))
    return inputs


def find_distributed_lookup_table_outputs(program, table_name):
    """All Out variables produced by lookup_table ops over ``table_name``."""
    local_vars = program.current_block().vars
    outputs = []
    for op in _table_ops(program, table_name):
        outputs.extend(local_vars[name] for name in op.output("Out"))
    return outputs


def find_distributed_lookup_table(program):
    """-> the unique distributed table's parameter name, or None.  Raises
    if two different tables are marked distributed (only one supported)."""
    table_name = None
    for op in program.global_block().ops:
        if op.type != LOOKUP_TABLE_TYPE:
            continue
        if op.attr("is_distributed") is True:
            if table_name is None:
                table_name = op.input("W")[0]
            if table_name != op.input("W")[0]:
                raise RuntimeError("all distributed lookup_table_ops"
                                   " should have only one table")
        else:
            if table_name is not None:
                assert op.input("W")[0] != table_name
    return table_name
