"""Build helpers for the C API library and the standalone C++ demo trainer
(parity: cmake/generic.cmake cc_library/cc_binary for c_api.cc +
train/demo/CMakeLists)."""

import os
import subprocess
import sysconfig

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_CAPI_SRC = os.path.join(_HERE, "csrc_capi", "paddle_tpu_c.cc")
_CAPI_LIB = os.path.join(_HERE, "_libpaddle_tpu_c.so")


def _py_flags():
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION")
    return ["-I" + inc], ["-L" + libdir, "-Wl,-rpath," + libdir,
                          "-lpython" + ver, "-ldl", "-lm"]


def build_capi(force=False):
    """Compile native/csrc_capi/paddle_tpu_c.cc -> _libpaddle_tpu_c.so."""
    if not force and os.path.exists(_CAPI_LIB) and (
            os.path.getmtime(_CAPI_LIB) >= os.path.getmtime(_CAPI_SRC)):
        return _CAPI_LIB
    cflags, ldflags = _py_flags()
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           *cflags, _CAPI_SRC, "-o", _CAPI_LIB + ".tmp", *ldflags]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(_CAPI_LIB + ".tmp", _CAPI_LIB)
    return _CAPI_LIB


def build_demo_trainer(out_path=None, force=False):
    """Compile tools/demo_trainer.cc linking the C API library."""
    lib = build_capi(force=force)
    src = os.path.join(_REPO, "tools", "demo_trainer.cc")
    out = out_path or os.path.join(_HERE, "_demo_trainer")
    if not force and os.path.exists(out) and (
            os.path.getmtime(out) >= max(os.path.getmtime(src),
                                         os.path.getmtime(lib))):
        return out
    cmd = ["g++", "-O2", "-std=c++17", src, lib,
           "-Wl,-rpath," + os.path.dirname(lib), "-o", out + ".tmp"]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(out + ".tmp", out)
    return out
