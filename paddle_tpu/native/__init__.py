"""Native (C++) runtime components, loaded via ctypes.

The reference keeps its data hand-off and dataset parsing in C++
(paddle/fluid/operators/reader/blocking_queue.h,
paddle/fluid/framework/data_feed.cc); so do we.  Sources live in
``csrc/`` and are compiled on first import with g++ into a cached shared
library (no pybind11 in this image — plain C ABI + ctypes).
"""

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.join(_HERE, "csrc")


def _lib_dir():
    """Build output location: next to the sources when writable (dev
    checkout), else a per-user cache dir (read-only wheel installs)."""
    if os.access(_HERE, os.W_OK):
        return _HERE
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.join(os.path.expanduser("~"), ".cache")),
        "paddle_tpu")
    os.makedirs(cache, exist_ok=True)
    return cache


_LIB_PATH = os.path.join(_lib_dir(), "_libpaddle_tpu_native.so")

_lib = None
_lib_lock = threading.Lock()


def _sources():
    return sorted(
        os.path.join(_CSRC, f) for f in os.listdir(_CSRC) if f.endswith(".cc")
    )


def _needs_build():
    if not os.path.exists(_LIB_PATH):
        return True
    so_mtime = os.path.getmtime(_LIB_PATH)
    return any(os.path.getmtime(s) > so_mtime for s in _sources())


def _build():
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        *_sources(), "-o", _LIB_PATH + ".tmp",
    ]
    # _lib_lock exists precisely to serialize this one-time g++ build;
    # blocking under it is the invariant (a second importer must wait for
    # the .so, not race the compiler), and no other lock nests with it.
    subprocess.run(cmd, check=True, capture_output=True)  # threadlint: waive CC102 _lib_lock serializes the one-shot native build; waiting is the contract
    os.replace(_LIB_PATH + ".tmp", _LIB_PATH)  # threadlint: waive CC102 atomic publish of the .so must stay inside the build critical section


def _declare(lib):
    c = ctypes
    lib.dq_create.restype = c.c_void_p
    lib.dq_create.argtypes = [c.c_int]
    lib.dq_destroy.argtypes = [c.c_void_p]
    lib.dq_push.restype = c.c_int
    lib.dq_push.argtypes = [c.c_void_p, c.c_char_p, c.c_int64, c.c_int]
    lib.dq_pop.restype = c.c_int64
    lib.dq_pop.argtypes = [c.c_void_p, c.POINTER(c.c_void_p), c.c_int]
    lib.dq_free.argtypes = [c.c_void_p]
    lib.dq_close.argtypes = [c.c_void_p]
    lib.dq_kill.argtypes = [c.c_void_p]
    lib.dq_reopen.argtypes = [c.c_void_p]
    lib.dq_size.restype = c.c_int
    lib.dq_size.argtypes = [c.c_void_p]
    lib.dq_is_closed.restype = c.c_int
    lib.dq_is_closed.argtypes = [c.c_void_p]

    lib.ms_create.restype = c.c_void_p
    lib.ms_create.argtypes = [c.c_int, c.POINTER(c.c_int)]
    lib.ms_destroy.argtypes = [c.c_void_p]
    lib.ms_load_file.restype = c.c_int64
    lib.ms_load_file.argtypes = [c.c_void_p, c.c_char_p]
    lib.ms_num_records.restype = c.c_int64
    lib.ms_num_records.argtypes = [c.c_void_p]
    lib.ms_shuffle.argtypes = [c.c_void_p, c.c_uint64]
    lib.ms_clear.argtypes = [c.c_void_p]
    lib.ms_batch_slot_len.restype = c.c_int64
    lib.ms_batch_slot_len.argtypes = [c.c_void_p, c.c_int64, c.c_int64, c.c_int]
    lib.ms_batch_fill.argtypes = [
        c.c_void_p, c.c_int64, c.c_int64, c.c_int, c.c_void_p,
        c.POINTER(c.c_int64),
    ]

    # tensor RPC (tensor_rpc.cc) — PS transport
    lib.rpcs_create.restype = c.c_void_p
    lib.rpcs_create.argtypes = [c.c_int]
    lib.rpcs_port.restype = c.c_int
    lib.rpcs_port.argtypes = [c.c_void_p]
    lib.rpcs_poll.restype = c.c_int
    lib.rpcs_poll.argtypes = [
        c.c_void_p, c.c_char_p, c.c_int, c.POINTER(c.c_ubyte),
        c.POINTER(c.c_longlong), c.c_int, c.POINTER(c.c_int),
        c.POINTER(c.c_void_p), c.POINTER(c.c_longlong),
    ]
    lib.rpcs_set_var.argtypes = [
        c.c_void_p, c.c_char_p, c.c_ubyte, c.POINTER(c.c_longlong),
        c.c_int, c.c_void_p, c.c_longlong,
    ]
    lib.rpcs_serve.argtypes = [c.c_void_p, c.c_int]
    lib.rpcs_del_var.argtypes = [c.c_void_p, c.c_char_p]
    lib.rpcs_destroy.argtypes = [c.c_void_p]
    lib.rpcc_connect.restype = c.c_void_p
    lib.rpcc_connect.argtypes = [c.c_char_p, c.c_int]
    lib.rpcc_send_var.restype = c.c_int
    lib.rpcc_send_var.argtypes = [
        c.c_void_p, c.c_char_p, c.c_ubyte, c.POINTER(c.c_longlong),
        c.c_int, c.c_void_p, c.c_longlong,
    ]
    lib.rpcc_barrier.restype = c.c_int
    lib.rpcc_barrier.argtypes = [c.c_void_p, c.c_char_p]
    lib.rpcc_complete.restype = c.c_int
    lib.rpcc_complete.argtypes = [c.c_void_p]
    lib.rpcc_get_var.restype = c.c_longlong
    lib.rpcc_get_var.argtypes = [
        c.c_void_p, c.c_char_p, c.POINTER(c.c_ubyte),
        c.POINTER(c.c_longlong), c.c_int, c.POINTER(c.c_int),
        c.POINTER(c.c_void_p),
    ]
    lib.rpc_free.argtypes = [c.c_void_p]
    lib.rpcc_set_deadline.argtypes = [c.c_void_p, c.c_double]
    lib.rpcc_close.argtypes = [c.c_void_p]


def load():
    """Compile (if stale) and load the native library. Thread-safe."""
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _needs_build():
            _build()
        lib = ctypes.CDLL(_LIB_PATH)
        _declare(lib)
        _lib = lib
    return _lib


def available():
    """True when the native library can be built/loaded on this machine."""
    try:
        load()
        return True
    except Exception:
        return False
