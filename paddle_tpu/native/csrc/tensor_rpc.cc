// Tensor RPC transport for the parameter-server runtime.
//
// TPU-native analog of the reference's RPC layer
// (paddle/fluid/operators/distributed/: grpc_client.cc / grpc_server.cc /
// variable_response.cc wire format, request_handler_impl.cc Send/Get
// handlers).  gRPC/BRPC are replaced by a framed TCP protocol; the server is
// dumb transport + tensor store + event queue, and the pserver's optimizer
// blocks run in Python against the normal executor (mirroring the reference,
// where listen_and_serv_op.cc executes optimizer sub-blocks per received
// grad while the transport lives in C++).
//
// Wire frame: [u8 type][u32 name_len][name][u8 dtype][u8 ndim][i64 dims...]
//             [u64 payload_len][payload]
// types: 1=SEND_VAR 2=GET_VAR 3=BARRIER 4=COMPLETE 5=REPLY_VAR 6=ACK
//
// C ABI (ctypes): rpcs_* = server, rpcc_* = client.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint8_t kSendVar = 1, kGetVar = 2, kBarrier = 3, kComplete = 4,
                  kReplyVar = 5, kAck = 6;

struct Tensor {
  uint8_t dtype = 0;  // opaque to the transport (numpy dtype enum on the py side)
  std::vector<int64_t> dims;
  std::string data;
};

struct Event {  // delivered to the Python pserver loop
  uint8_t type;  // kSendVar | kBarrier | kComplete
  std::string name;
  Tensor tensor;  // valid for kSendVar
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct Frame {
  uint8_t type = 0;
  std::string name;
  Tensor tensor;
};

bool read_frame(int fd, Frame* f) {
  uint8_t type;
  if (!read_full(fd, &type, 1)) return false;
  uint32_t name_len;
  if (!read_full(fd, &name_len, 4)) return false;
  if (name_len > (1u << 20)) return false;
  f->name.resize(name_len);
  if (name_len && !read_full(fd, f->name.data(), name_len)) return false;
  uint8_t dtype, ndim;
  if (!read_full(fd, &dtype, 1) || !read_full(fd, &ndim, 1)) return false;
  f->tensor.dtype = dtype;
  f->tensor.dims.resize(ndim);
  if (ndim && !read_full(fd, f->tensor.dims.data(), 8ull * ndim)) return false;
  uint64_t payload;
  if (!read_full(fd, &payload, 8)) return false;
  if (payload > (1ull << 33)) return false;
  f->tensor.data.resize(payload);
  if (payload && !read_full(fd, f->tensor.data.data(), payload)) return false;
  f->type = type;
  return true;
}

bool write_frame(int fd, uint8_t type, const std::string& name,
                 const Tensor* t) {
  std::string head;
  head.push_back(static_cast<char>(type));
  uint32_t name_len = static_cast<uint32_t>(name.size());
  head.append(reinterpret_cast<char*>(&name_len), 4);
  head += name;
  uint8_t dtype = t ? t->dtype : 0;
  uint8_t ndim = t ? static_cast<uint8_t>(t->dims.size()) : 0;
  head.push_back(static_cast<char>(dtype));
  head.push_back(static_cast<char>(ndim));
  if (t && ndim)
    head.append(reinterpret_cast<const char*>(t->dims.data()), 8ull * ndim);
  uint64_t payload = t ? t->data.size() : 0;
  head.append(reinterpret_cast<char*>(&payload), 8);
  if (!write_full(fd, head.data(), head.size())) return false;
  if (t && payload) return write_full(fd, t->data.data(), payload);
  return true;
}

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::thread accept_thread;
  std::vector<std::thread> conns;
  std::vector<int> conn_fds;  // so destroy can unblock idle recv()s
  std::mutex mu;
  std::condition_variable events_cv;   // Python waits for inbound events
  std::condition_variable store_cv;    // GET handlers wait for published vars
  std::deque<Event> events;
  std::map<std::string, Tensor> store;
  bool serving = false;  // GETs blocked until Python publishes + enables
  bool stop = false;

  void forget_fd(int fd) {
    std::lock_guard<std::mutex> lk(mu);
    for (auto it = conn_fds.begin(); it != conn_fds.end(); ++it) {
      if (*it == fd) {
        conn_fds.erase(it);
        break;
      }
    }
  }

  void handle_conn(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    Frame f;
    while (read_frame(fd, &f)) {
      if (f.type == kSendVar || f.type == kBarrier || f.type == kComplete) {
        {
          std::lock_guard<std::mutex> lk(mu);
          events.push_back({f.type, f.name, std::move(f.tensor)});
        }
        events_cv.notify_all();
        if (!write_frame(fd, kAck, "", nullptr)) break;
      } else if (f.type == kGetVar) {
        Tensor t;
        {
          std::unique_lock<std::mutex> lk(mu);
          store_cv.wait(lk, [&] {
            return stop || (serving && store.count(f.name));
          });
          if (stop) break;
          t = store[f.name];
        }
        if (!write_frame(fd, kReplyVar, f.name, &t)) break;
      }
    }
    // drop from conn_fds BEFORE closing: destroy() must never shutdown()
    // a number the OS may have already reassigned to an unrelated socket
    forget_fd(fd);
    ::close(fd);
  }

  void accept_loop() {
    while (true) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        std::lock_guard<std::mutex> lk(mu);
        if (stop) return;
        continue;
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        if (stop) {
          ::close(fd);
          return;
        }
        conn_fds.push_back(fd);
        conns.emplace_back(&Server::handle_conn, this, fd);
      }
    }
  }
};

struct Client {
  int fd = -1;
};

}  // namespace

extern "C" {

// -- server ------------------------------------------------------------------

void* rpcs_create(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 64) != 0) {
    ::close(fd);
    return nullptr;
  }
  auto* s = new Server();
  s->listen_fd = fd;
  if (port == 0) {
    socklen_t len = sizeof(addr);
    ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  }
  s->port = ntohs(addr.sin_port);
  s->accept_thread = std::thread(&Server::accept_loop, s);
  return s;
}

int rpcs_port(void* h) { return static_cast<Server*>(h)->port; }

// Blocking poll for the next inbound event.  Returns the event type (0 on
// shutdown).  Name is copied into name_buf; SEND_VAR tensors are held until
// the next rpcs_poll call via *data/*dims outputs.
int rpcs_poll(void* h, char* name_buf, int name_cap, unsigned char* dtype,
              long long* dims, int dims_cap, int* ndim,
              const void** data, long long* data_len) {
  auto* s = static_cast<Server*>(h);
  static thread_local Event current;  // keeps tensor alive for the caller
  std::unique_lock<std::mutex> lk(s->mu);
  s->events_cv.wait(lk, [&] { return s->stop || !s->events.empty(); });
  if (s->stop && s->events.empty()) return 0;
  current = std::move(s->events.front());
  s->events.pop_front();
  lk.unlock();
  std::snprintf(name_buf, name_cap, "%s", current.name.c_str());
  *dtype = current.tensor.dtype;
  *ndim = static_cast<int>(current.tensor.dims.size());
  for (int i = 0; i < *ndim && i < dims_cap; ++i)
    dims[i] = current.tensor.dims[i];
  *data = current.tensor.data.data();
  *data_len = static_cast<long long>(current.tensor.data.size());
  return current.type;
}

void rpcs_set_var(void* h, const char* name, unsigned char dtype,
                  const long long* dims, int ndim, const void* data,
                  long long len) {
  auto* s = static_cast<Server*>(h);
  Tensor t;
  t.dtype = dtype;
  t.dims.assign(dims, dims + ndim);
  t.data.assign(static_cast<const char*>(data), static_cast<size_t>(len));
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->store[name] = std::move(t);
  }
  s->store_cv.notify_all();
}

void rpcs_del_var(void* h, const char* name) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->store.erase(name);
}

void rpcs_serve(void* h, int enable) {
  auto* s = static_cast<Server*>(h);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->serving = enable != 0;
  }
  s->store_cv.notify_all();
}

void rpcs_destroy(void* h) {
  auto* s = static_cast<Server*>(h);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->stop = true;
    // unblock handler threads parked in recv() on idle connections —
    // joining without this deadlocks when a client is mid-compute
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  s->store_cv.notify_all();
  s->events_cv.notify_all();
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  for (auto& t : s->conns)
    if (t.joinable()) t.join();
  delete s;
}

// -- client ------------------------------------------------------------------

void* rpcc_connect(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return nullptr;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return nullptr;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Client();
  c->fd = fd;
  return c;
}

// Per-request deadline (reference FLAGS_rpc_deadline,
// paddle/fluid/operators/distributed/grpc/grpc_client.cc): a pserver that
// hangs mid-round must surface as an error on the trainer, not block its
// recv() forever.  seconds <= 0 restores fully-blocking behavior.
void rpcc_set_deadline(void* h, double seconds) {
  auto* c = static_cast<Client*>(h);
  timeval tv{};
  if (seconds > 0) {
    tv.tv_sec = static_cast<time_t>(seconds);
    tv.tv_usec =
        static_cast<suseconds_t>((seconds - static_cast<double>(tv.tv_sec)) * 1e6);
  }
  ::setsockopt(c->fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(c->fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

int rpcc_send_var(void* h, const char* name, unsigned char dtype,
                  const long long* dims, int ndim, const void* data,
                  long long len) {
  auto* c = static_cast<Client*>(h);
  Tensor t;
  t.dtype = dtype;
  t.dims.assign(dims, dims + ndim);
  t.data.assign(static_cast<const char*>(data), static_cast<size_t>(len));
  if (!write_frame(c->fd, kSendVar, name, &t)) return -1;
  Frame ack;
  if (!read_frame(c->fd, &ack) || ack.type != kAck) return -1;
  return 0;
}

int rpcc_barrier(void* h, const char* kind) {
  auto* c = static_cast<Client*>(h);
  if (!write_frame(c->fd, kBarrier, kind, nullptr)) return -1;
  Frame ack;
  if (!read_frame(c->fd, &ack) || ack.type != kAck) return -1;
  return 0;
}

int rpcc_complete(void* h) {
  auto* c = static_cast<Client*>(h);
  if (!write_frame(c->fd, kComplete, "", nullptr)) return -1;
  Frame ack;
  if (!read_frame(c->fd, &ack) || ack.type != kAck) return -1;
  return 0;
}

// Blocking GET: fills dtype/dims/ndim, returns a malloc'd payload pointer in
// *data (caller frees with rpc_free) and the byte length (<0 on error).
long long rpcc_get_var(void* h, const char* name, unsigned char* dtype,
                       long long* dims, int dims_cap, int* ndim,
                       void** data) {
  auto* c = static_cast<Client*>(h);
  if (!write_frame(c->fd, kGetVar, name, nullptr)) return -1;
  Frame f;
  if (!read_frame(c->fd, &f) || f.type != kReplyVar) return -1;
  *dtype = f.tensor.dtype;
  *ndim = static_cast<int>(f.tensor.dims.size());
  for (int i = 0; i < *ndim && i < dims_cap; ++i) dims[i] = f.tensor.dims[i];
  void* buf = ::malloc(f.tensor.data.size() ? f.tensor.data.size() : 1);
  std::memcpy(buf, f.tensor.data.data(), f.tensor.data.size());
  *data = buf;
  return static_cast<long long>(f.tensor.data.size());
}

void rpc_free(void* p) { ::free(p); }

void rpcc_close(void* h) {
  auto* c = static_cast<Client*>(h);
  ::close(c->fd);
  delete c;
}

}  // extern "C"
