// Native data-pipeline runtime: bounded blocking byte-buffer queue.
//
// TPU-native analog of the reference's LoDTensorBlockingQueue
// (paddle/fluid/operators/reader/lod_tensor_blocking_queue.h) +
// BlockingQueue (paddle/fluid/operators/reader/blocking_queue.h): the C++
// hand-off between Python-side data producers and the device feed path.
// Buffers are opaque byte blobs (the Python layer packs batches of ndarrays
// with a small header); the queue owns copies, so producers can recycle
// their memory immediately.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>

namespace {

struct Buffer {
  char* data;
  int64_t len;
};

struct Queue {
  explicit Queue(int capacity) : cap(capacity) {}
  ~Queue() {
    for (auto& b : items) delete[] b.data;
  }

  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
  std::deque<Buffer> items;
  int cap;
  bool closed = false;   // no more pushes; pops drain whatever remains
  bool killed = false;   // immediate shutdown, pending items dropped
};

}  // namespace

extern "C" {

void* dq_create(int capacity) { return new Queue(capacity > 0 ? capacity : 1); }

void dq_destroy(void* q) { delete static_cast<Queue*>(q); }

// 0 = ok, -1 = closed/killed, -2 = timeout. timeout_ms < 0 means block forever.
int dq_push(void* qp, const void* data, int64_t len, int timeout_ms) {
  Queue* q = static_cast<Queue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  auto ready = [q] {
    return q->closed || q->killed || static_cast<int>(q->items.size()) < q->cap;
  };
  if (timeout_ms < 0) {
    q->not_full.wait(lk, ready);
  } else if (!q->not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   ready)) {
    return -2;
  }
  if (q->closed || q->killed) return -1;
  Buffer b;
  b.len = len;
  b.data = new char[len > 0 ? len : 1];
  std::memcpy(b.data, data, static_cast<size_t>(len));
  q->items.push_back(b);
  q->not_empty.notify_one();
  return 0;
}

// >= 0: buffer length, *out set to a malloc'd buffer the caller must free
// with dq_free. -1 = closed-and-drained/killed, -2 = timeout.
int64_t dq_pop(void* qp, void** out, int timeout_ms) {
  Queue* q = static_cast<Queue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  auto ready = [q] { return q->killed || q->closed || !q->items.empty(); };
  if (timeout_ms < 0) {
    q->not_empty.wait(lk, ready);
  } else if (!q->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                    ready)) {
    return -2;
  }
  if (q->killed || (q->items.empty() && q->closed)) return -1;
  if (q->items.empty()) return -2;
  Buffer b = q->items.front();
  q->items.pop_front();
  q->not_full.notify_one();
  *out = b.data;
  return b.len;
}

void dq_free(void* buf) { delete[] static_cast<char*>(buf); }

// Graceful close: producers stop, consumers drain what is left.
void dq_close(void* qp) {
  Queue* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  q->closed = true;
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

// Immediate shutdown, dropping pending items (DataLoader reset()).
void dq_kill(void* qp) {
  Queue* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  q->killed = true;
  for (auto& b : q->items) delete[] b.data;
  q->items.clear();
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

// Reopen after kill/close (queue reuse across epochs).
void dq_reopen(void* qp) {
  Queue* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  for (auto& b : q->items) delete[] b.data;
  q->items.clear();
  q->closed = false;
  q->killed = false;
}

int dq_size(void* qp) {
  Queue* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  return static_cast<int>(q->items.size());
}

int dq_is_closed(void* qp) {
  Queue* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->closed || q->killed;
}

}  // extern "C"
