// Native MultiSlot dataset store: load + parse slot-format text files,
// in-memory shuffle, batch extraction.
//
// TPU-native analog of the reference's C++ data feed
// (paddle/fluid/framework/data_feed.h:222 InMemoryDataFeed,
// :532 MultiSlotDataFeed; paddle/fluid/framework/data_set.h:135 DatasetImpl
// LoadIntoMemory/LocalShuffle).  Line format, per record:
//   for each slot: "<n> <v_1> ... <v_n>"
// with slot types declared up front (0 = int64 ids, 1 = float values).
// Parsing and shuffling happen in C++ off the Python GIL; Python pulls
// padded/concatenated batches through the C ABI below (ctypes).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <vector>

namespace {

struct Record {
  // per slot: offset+count into the type-specific value pools
  std::vector<int64_t> offset;
  std::vector<int64_t> count;
};

struct Store {
  std::vector<int> types;  // 0 = int64, 1 = float
  std::vector<int64_t> ipool;
  std::vector<float> fpool;
  std::vector<Record> records;
};

}  // namespace

extern "C" {

void* ms_create(int nslots, const int* types) {
  Store* s = new Store();
  s->types.assign(types, types + nslots);
  return s;
}

void ms_destroy(void* sp) { delete static_cast<Store*>(sp); }

// Returns number of records parsed, or -1 on open failure / parse error.
int64_t ms_load_file(void* sp, const char* path) {
  Store* s = static_cast<Store*>(sp);
  FILE* f = std::fopen(path, "r");
  if (!f) return -1;
  int64_t added = 0;
  char* line = nullptr;
  size_t cap = 0;
  ssize_t n;
  const int nslots = static_cast<int>(s->types.size());
  while ((n = getline(&line, &cap, f)) != -1) {
    if (n <= 1) continue;
    char* p = line;
    Record rec;
    rec.offset.resize(nslots);
    rec.count.resize(nslots);
    bool ok = true;
    for (int slot = 0; slot < nslots && ok; ++slot) {
      char* end;
      long cnt = std::strtol(p, &end, 10);
      if (end == p || cnt < 0) {
        ok = false;
        break;
      }
      p = end;
      rec.count[slot] = cnt;
      if (s->types[slot] == 0) {
        rec.offset[slot] = static_cast<int64_t>(s->ipool.size());
        for (long i = 0; i < cnt; ++i) {
          long long v = std::strtoll(p, &end, 10);
          if (end == p) {
            ok = false;
            break;
          }
          p = end;
          s->ipool.push_back(v);
        }
      } else {
        rec.offset[slot] = static_cast<int64_t>(s->fpool.size());
        for (long i = 0; i < cnt; ++i) {
          float v = std::strtof(p, &end);
          if (end == p) {
            ok = false;
            break;
          }
          p = end;
          s->fpool.push_back(v);
        }
      }
    }
    if (ok) {
      s->records.push_back(std::move(rec));
      ++added;
    }
  }
  free(line);
  std::fclose(f);
  return added;
}

int64_t ms_num_records(void* sp) {
  return static_cast<int64_t>(static_cast<Store*>(sp)->records.size());
}

void ms_shuffle(void* sp, uint64_t seed) {
  Store* s = static_cast<Store*>(sp);
  std::mt19937_64 rng(seed);
  std::shuffle(s->records.begin(), s->records.end(), rng);
}

void ms_clear(void* sp) {
  Store* s = static_cast<Store*>(sp);
  s->records.clear();
  s->ipool.clear();
  s->fpool.clear();
}

// Total number of values of `slot` across records [begin, end).
int64_t ms_batch_slot_len(void* sp, int64_t begin, int64_t end, int slot) {
  Store* s = static_cast<Store*>(sp);
  int64_t total = 0;
  for (int64_t r = begin; r < end && r < (int64_t)s->records.size(); ++r)
    total += s->records[r].count[slot];
  return total;
}

// Fill `values_out` (int64_t* or float* matching the slot type) with the
// concatenated values of `slot` over records [begin, end), and
// `lengths_out[i]` with each record's count (ragged batch lengths — the
// LoD analog that the Python layer pads/masks for XLA static shapes).
void ms_batch_fill(void* sp, int64_t begin, int64_t end, int slot,
                   void* values_out, int64_t* lengths_out) {
  Store* s = static_cast<Store*>(sp);
  int64_t vi = 0;
  for (int64_t r = begin; r < end && r < (int64_t)s->records.size(); ++r) {
    const Record& rec = s->records[r];
    int64_t cnt = rec.count[slot];
    lengths_out[r - begin] = cnt;
    if (s->types[slot] == 0) {
      std::memcpy(static_cast<int64_t*>(values_out) + vi,
                  s->ipool.data() + rec.offset[slot], cnt * sizeof(int64_t));
    } else {
      std::memcpy(static_cast<float*>(values_out) + vi,
                  s->fpool.data() + rec.offset[slot], cnt * sizeof(float));
    }
    vi += cnt;
  }
}

}  // extern "C"
