// C API for paddle_tpu (parity: paddle/fluid/framework/c/c_api.cc op-info
// query + inference/capi/ predictor C bindings + train/demo C++ training).
//
// Design: the compute substrate is XLA/PJRT reached through the Python
// runtime, so this library embeds CPython and marshals C buffers to
// paddle_tpu.capi_host.  Everything exported here is plain C ABI — usable
// from C, C++, Rust-ffi, dlopen, etc.
//
// Build: native/capi.py::build_capi() (g++ + python3-config --embed flags).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cmath>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

std::once_flag g_init_once;
PyObject* g_host = nullptr;  // paddle_tpu.capi_host module

void EnsureInit(const char* repo_root) {
  std::call_once(g_init_once, [repo_root] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
    }
    PyGILState_STATE s = PyGILState_Ensure();
    if (repo_root && *repo_root) {
      PyObject* sys_path = PySys_GetObject("path");  // borrowed
      PyObject* p = PyUnicode_FromString(repo_root);
      PyList_Insert(sys_path, 0, p);
      Py_DECREF(p);
    }
    g_host = PyImport_ImportModule("paddle_tpu.capi_host");
    if (!g_host) PyErr_Print();
    PyGILState_Release(s);
    // Hand the GIL to whichever thread calls next.
    if (PyGILState_Check()) {
      PyEval_SaveThread();
    }
  });
}

// Call host.fn(args...) -> new ref (nullptr on error, with error printed)
PyObject* Call(const char* fn, PyObject* args) {
  if (!g_host) {  // PT_Init not called, or the host module failed to import
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* f = PyObject_GetAttrString(g_host, fn);
  if (!f) {
    PyErr_Print();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (!r) PyErr_Print();
  return r;
}

// NB: args must be built while holding the GIL — these helpers take a
// format string + varargs and do Py_VaBuildValue inside Ensure/Release.
int64_t CallI64(const char* fn, const char* fmt, ...) {
  PyGILState_STATE s = PyGILState_Ensure();
  va_list va;
  va_start(va, fmt);
  PyObject* args = fmt ? Py_VaBuildValue(fmt, va) : PyTuple_New(0);
  va_end(va);
  PyObject* r = Call(fn, args);
  int64_t out = r ? PyLong_AsLongLong(r) : -1;
  Py_XDECREF(r);
  PyGILState_Release(s);
  return out;
}

double CallF64(const char* fn, const char* fmt, ...) {
  PyGILState_STATE s = PyGILState_Ensure();
  va_list va;
  va_start(va, fmt);
  PyObject* args = fmt ? Py_VaBuildValue(fmt, va) : PyTuple_New(0);
  va_end(va);
  PyObject* r = Call(fn, args);
  // NAN on failure: a poisoned value can't satisfy accuracy checks the way
  // a numeric sentinel could
  double out = r ? PyFloat_AsDouble(r) : std::nan("");
  Py_XDECREF(r);
  PyGILState_Release(s);
  return out;
}

}  // namespace

extern "C" {

// Initialize the runtime. repo_root: directory containing paddle_tpu/
// (may be "" if already importable). Safe to call multiple times.
void PT_Init(const char* repo_root) { EnsureInit(repo_root); }

// -- op registry query --------------------------------------------------------

int64_t PT_NumOps() { return CallI64("num_ops", nullptr); }

// Write newline-separated op names into buf (truncated to buf_len).
// Returns the untruncated length.
int64_t PT_OpNames(char* buf, int64_t buf_len) {
  PyGILState_STATE s = PyGILState_Ensure();
  PyObject* r = Call("op_names", PyTuple_New(0));
  int64_t full = -1;
  if (r) {
    Py_ssize_t n = 0;
    const char* str = PyUnicode_AsUTF8AndSize(r, &n);
    full = static_cast<int64_t>(n);
    if (buf && buf_len > 0) {
      int64_t c = full < buf_len - 1 ? full : buf_len - 1;
      std::memcpy(buf, str, static_cast<size_t>(c));
      buf[c] = '\0';
    }
    Py_DECREF(r);
  }
  PyGILState_Release(s);
  return full;
}

// -- trainer ------------------------------------------------------------------

// place: "cpu" or "tpu". Returns handle > 0, or <= 0 on failure.
int64_t PT_TrainerCreate(const char* model_dir, const char* place) {
  return CallI64("trainer_create", "(ss)", model_dir, place);
}

// dtype: "float32" | "float64" | "int32" | "int64"
int PT_Feed(int64_t handle, const char* name, const void* data,
            const char* dtype, const int64_t* dims, int ndim) {
  PyGILState_STATE s = PyGILState_Ensure();
  int64_t elems = 1;
  for (int i = 0; i < ndim; ++i) elems *= dims[i];
  int64_t esize = (std::strcmp(dtype, "float64") == 0 ||
                   std::strcmp(dtype, "int64") == 0)
                      ? 8
                      : 4;
  PyObject* dims_list = PyList_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyList_SetItem(dims_list, i, PyLong_FromLongLong(dims[i]));
  }
  PyObject* args = Py_BuildValue(
      "(Lsy#sN)", static_cast<long long>(handle), name,
      static_cast<const char*>(data),
      static_cast<Py_ssize_t>(elems * esize), dtype, dims_list);
  PyObject* r = Call("feed_buffer", args);
  int ok = r ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(s);
  return ok;
}

// Run one training step; returns the first fetch (the loss) as double.
double PT_TrainerStep(int64_t handle) {
  return CallF64("trainer_step", "(L)", static_cast<long long>(handle));
}

// -- predictor ----------------------------------------------------------------

int64_t PT_PredictorCreate(const char* model_dir, const char* place) {
  return CallI64("predictor_create", "(ss)", model_dir, place);
}

// Returns number of outputs, or -1.
int64_t PT_PredictorRun(int64_t handle) {
  return CallI64("predictor_run", "(L)", static_cast<long long>(handle));
}

int64_t PT_OutputNdim(int64_t handle, int64_t i) {
  return CallI64("output_ndim", "(LL)", static_cast<long long>(handle),
                 static_cast<long long>(i));
}

int64_t PT_OutputDim(int64_t handle, int64_t i, int64_t d) {
  return CallI64("output_dim", "(LLL)", static_cast<long long>(handle),
                 static_cast<long long>(i), static_cast<long long>(d));
}

// Copy output i (as float32) into buf; returns number of bytes copied.
int64_t PT_OutputCopy(int64_t handle, int64_t i, void* buf, int64_t buf_len) {
  PyGILState_STATE s = PyGILState_Ensure();
  PyObject* r = Call("output_bytes",
                     Py_BuildValue("(LL)", static_cast<long long>(handle),
                                   static_cast<long long>(i)));
  int64_t copied = -1;
  if (r) {
    char* bytes = nullptr;
    Py_ssize_t n = 0;
    if (PyBytes_AsStringAndSize(r, &bytes, &n) == 0) {
      copied = n < buf_len ? n : buf_len;
      std::memcpy(buf, bytes, static_cast<size_t>(copied));
    }
    Py_DECREF(r);
  }
  PyGILState_Release(s);
  return copied;
}

int PT_Destroy(int64_t handle) {
  return static_cast<int>(
      CallI64("destroy", "(L)", static_cast<long long>(handle)));
}

}  // extern "C"
