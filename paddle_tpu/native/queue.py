"""Python wrapper over the native blocking queue: batches of ndarrays
cross the producer/consumer boundary as single contiguous byte buffers.

Analog of the reference's LoDTensorBlockingQueue hand-off
(operators/reader/lod_tensor_blocking_queue.h) with the tensor wire header
playing the role of the LoDTensor serialization (framework/lod_tensor.h:208).
"""

import ctypes
import struct

import numpy as np

from . import load

__all__ = ["NativeBlockingQueue", "QueueClosed"]


class QueueClosed(Exception):
    pass


def _pack(arrays):
    parts = [struct.pack("<i", len(arrays))]
    for a in arrays:
        a = np.asarray(a)
        if a.ndim and not a.flags["C_CONTIGUOUS"]:
            a = np.ascontiguousarray(a)
        dt = a.dtype.str.encode()  # e.g. b"<f4"
        parts.append(struct.pack("<i", len(dt)))
        parts.append(dt)
        parts.append(struct.pack("<i", a.ndim))
        parts.append(struct.pack("<%dq" % a.ndim, *a.shape))
        parts.append(a.tobytes())
    return b"".join(parts)


def _unpack(buf):
    off = 0
    (n,) = struct.unpack_from("<i", buf, off)
    off += 4
    out = []
    for _ in range(n):
        (dtlen,) = struct.unpack_from("<i", buf, off)
        off += 4
        dt = np.dtype(buf[off:off + dtlen].decode())
        off += dtlen
        (ndim,) = struct.unpack_from("<i", buf, off)
        off += 4
        shape = struct.unpack_from("<%dq" % ndim, buf, off)
        off += 8 * ndim
        nvals = int(np.prod(shape)) if ndim else 1
        arr = np.frombuffer(buf, dtype=dt, count=nvals, offset=off)
        out.append(arr.reshape(shape))
        off += nvals * dt.itemsize
    return out


class NativeBlockingQueue:
    """Bounded blocking queue of ndarray batches backed by C++."""

    def __init__(self, capacity=64):
        self._lib = load()
        self._q = self._lib.dq_create(int(capacity))

    def push(self, arrays, timeout_ms=-1):
        buf = _pack(arrays)
        rc = self._lib.dq_push(self._q, buf, len(buf), timeout_ms)
        if rc == -1:
            raise QueueClosed()
        return rc == 0

    def pop(self, timeout_ms=-1):
        out = ctypes.c_void_p()
        n = self._lib.dq_pop(self._q, ctypes.byref(out), timeout_ms)
        if n == -1:
            raise QueueClosed()
        if n == -2:
            return None  # timeout
        try:
            buf = ctypes.string_at(out, n)
        finally:
            self._lib.dq_free(out)
        return _unpack(buf)

    def close(self):
        self._lib.dq_close(self._q)

    def kill(self):
        self._lib.dq_kill(self._q)

    def reopen(self):
        self._lib.dq_reopen(self._q)

    def size(self):
        return self._lib.dq_size(self._q)

    def is_closed(self):
        return bool(self._lib.dq_is_closed(self._q))

    def __del__(self):
        try:
            self._lib.dq_kill(self._q)
            self._lib.dq_destroy(self._q)
        except Exception:
            pass
