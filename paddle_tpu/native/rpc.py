"""Python wrappers over the native tensor-RPC transport (csrc/tensor_rpc.cc).

Analog of the reference's RPCClient/RPCServer interfaces
(paddle/fluid/operators/distributed/rpc_client.h, rpc_server.h) with the
VariableResponse-style tensor framing done in C++.
"""

import ctypes

import numpy as np

from . import load

__all__ = ["RpcServer", "RpcClient"]

# numpy dtype <-> wire enum
_DTYPES = ["float32", "float64", "int32", "int64", "uint8", "int8",
           "float16", "bool"]
_DT_TO_CODE = {np.dtype(d): i for i, d in enumerate(_DTYPES)}

EV_SEND = 1
EV_BARRIER = 3
EV_COMPLETE = 4


class RpcServer:
    def __init__(self, port=0):
        self._lib = load()
        self._h = self._lib.rpcs_create(int(port))
        if not self._h:
            raise OSError("cannot bind RPC server on port %d" % port)
        self.port = self._lib.rpcs_port(self._h)

    def poll(self):
        """Block for the next inbound event.
        Returns (type, name, array_or_None); type 0 => shutdown."""
        c = ctypes
        name = c.create_string_buffer(1024)
        dtype = c.c_ubyte()
        dims = (c.c_longlong * 16)()
        ndim = c.c_int()
        data = c.c_void_p()
        dlen = c.c_longlong()
        t = self._lib.rpcs_poll(self._h, name, 1024, c.byref(dtype), dims, 16,
                                c.byref(ndim), c.byref(data), c.byref(dlen))
        if t == 0:
            return 0, None, None
        arr = None
        if t == EV_SEND:
            shape = tuple(dims[i] for i in range(ndim.value))
            np_dt = np.dtype(_DTYPES[dtype.value])
            buf = ctypes.string_at(data.value, dlen.value)
            arr = np.frombuffer(buf, dtype=np_dt).reshape(shape).copy()
        return t, name.value.decode(), arr

    def set_var(self, name, arr):
        arr = np.ascontiguousarray(arr)
        dims = (ctypes.c_longlong * arr.ndim)(*arr.shape)
        self._lib.rpcs_set_var(
            self._h, name.encode(), _DT_TO_CODE[arr.dtype], dims, arr.ndim,
            arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes)

    def serve(self, enable=True):
        self._lib.rpcs_serve(self._h, 1 if enable else 0)

    def del_var(self, name):
        self._lib.rpcs_del_var(self._h, name.encode())

    def shutdown(self):
        if self._h:
            self._lib.rpcs_destroy(self._h)
            self._h = None


class RpcClient:
    def __init__(self, endpoint, connect_timeout=60.0, rpc_deadline=None):
        """Retries until the server is up (the reference client's
        wait-for-server behavior; grpc_client.cc connect deadline).

        rpc_deadline: per-REQUEST deadline in seconds; a pserver that hangs
        mid-round raises ConnectionError on the trainer instead of blocking
        forever (reference FLAGS_rpc_deadline + grpc_client.cc deadline
        handling).  None reads FLAGS_rpc_deadline (milliseconds, reference
        units; <=0 disables).  Semantics note: the deadline is enforced as
        a per-syscall IDLE timeout (SO_RCVTIMEO/SO_SNDTIMEO), not an
        elapsed-wall-clock deadline like the reference's gRPC one — a
        server that keeps trickling bytes resets it; a silent one trips
        it.  On the first deadline failure the client is POISONED (handle
        closed): the socket may be mid-frame, so retrying on it would
        silently desync framing; reconnect with a new RpcClient."""
        import time

        self._lib = load()
        host, port = endpoint.rsplit(":", 1)
        if host in ("localhost", ""):
            host = "127.0.0.1"
        deadline = time.time() + connect_timeout
        self._h = None
        while True:
            self._h = self._lib.rpcc_connect(host.encode(), int(port))
            if self._h or time.time() > deadline:
                break
            time.sleep(0.1)
        if not self._h:
            raise ConnectionError("cannot connect to pserver %s within %.0fs"
                                  % (endpoint, connect_timeout))
        self.endpoint = endpoint
        if rpc_deadline is None:
            from .. import flags as _flags

            ms = _flags.get_flags(["FLAGS_rpc_deadline"])[
                "FLAGS_rpc_deadline"]
            rpc_deadline = float(ms) / 1000.0 if ms and ms > 0 else 0.0
        self.rpc_deadline = float(rpc_deadline or 0.0)
        if self.rpc_deadline > 0:
            self._lib.rpcc_set_deadline(self._h, self.rpc_deadline)

    def _err(self, what):
        hint = (" (deadline %.0fs — pserver hung or connection lost)"
                % self.rpc_deadline if self.rpc_deadline > 0
                else " (connection lost)")
        # a timed-out socket may be mid-frame: a retried call on the same
        # connection would read misaligned frames (silent desync), so the
        # first failure poisons the client — callers must reconnect
        self.close()
        return ConnectionError("%s to %s failed%s"
                               % (what, self.endpoint, hint))

    def _check_open(self, what):
        if not self._h:
            raise ConnectionError(
                "%s to %s: client closed after a previous deadline/transport "
                "failure — reconnect with a new RpcClient" %
                (what, self.endpoint))

    def send_var(self, name, arr):
        self._check_open("send_var(%s)" % name)
        arr = np.ascontiguousarray(arr)
        dims = (ctypes.c_longlong * max(arr.ndim, 1))(*(arr.shape or (0,)))
        rc = self._lib.rpcc_send_var(
            self._h, name.encode(), _DT_TO_CODE[arr.dtype], dims, arr.ndim,
            arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes)
        if rc != 0:
            raise self._err("send_var(%s)" % name)

    def get_var(self, name):
        self._check_open("get_var(%s)" % name)
        c = ctypes
        dtype = c.c_ubyte()
        dims = (c.c_longlong * 16)()
        ndim = c.c_int()
        data = c.c_void_p()
        n = self._lib.rpcc_get_var(self._h, name.encode(), c.byref(dtype),
                                   dims, 16, c.byref(ndim), c.byref(data))
        if n < 0:
            raise self._err("get_var(%s)" % name)
        shape = tuple(dims[i] for i in range(ndim.value))
        buf = ctypes.string_at(data.value, n)
        self._lib.rpc_free(data)
        return np.frombuffer(buf, dtype=np.dtype(_DTYPES[dtype.value])) \
            .reshape(shape).copy()

    def barrier(self, kind):
        self._check_open("barrier(%s)" % kind)
        if self._lib.rpcc_barrier(self._h, kind.encode()) != 0:
            raise self._err("barrier(%s)" % kind)

    def complete(self):
        if not self._h:
            return  # fire-and-forget; tolerate a poisoned/closed client
        self._lib.rpcc_complete(self._h)

    def close(self):
        if self._h:
            self._lib.rpcc_close(self._h)
            self._h = None
