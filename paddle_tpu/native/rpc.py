"""Python wrappers over the native tensor-RPC transport (csrc/tensor_rpc.cc).

Analog of the reference's RPCClient/RPCServer interfaces
(paddle/fluid/operators/distributed/rpc_client.h, rpc_server.h) with the
VariableResponse-style tensor framing done in C++.
"""

import ctypes

import numpy as np

from . import load
from ..core import telemetry as _tm
from ..core import tracing as _tr
from ..utils.fault_injection import FaultInjected, maybe_fail

__all__ = ["RpcServer", "RpcClient", "backoff_delay", "probe"]


def probe(endpoint, key="__alive__", timeout=3.0):
    """One bounded GET of `key` against a server, None on any failure.

    The shared liveness-probe idiom of the elastic control plane and the
    serving fleet: connect fast (1 s), GET with a hard deadline, never
    retry — a dead, hung, or not-yet-listening server all read as None,
    and the probing caller decides what that means."""
    try:
        c = RpcClient(endpoint, connect_timeout=1.0, rpc_deadline=timeout,
                      retry_times=0)
    except ConnectionError:
        return None
    try:
        return c.get_var(key)
    except Exception:
        return None
    finally:
        try:
            c.close()
        except Exception:
            pass


def backoff_delay(attempt, base=0.05, cap=2.0, rng=None):
    """Exponential backoff with equal jitter for retry `attempt` (0-based):
    uniformly in [d/2, d] where d = min(cap, base * 2**attempt) — the
    reference client re-queues failed RPCs with a growing delay; jitter
    keeps N trainers retrying a recovered pserver from re-arriving in
    lockstep."""
    import random

    d = min(float(cap), float(base) * (2.0 ** attempt))
    r = (rng or random).random()
    return d * (0.5 + 0.5 * r)

# numpy dtype <-> wire enum
_DTYPES = ["float32", "float64", "int32", "int64", "uint8", "int8",
           "float16", "bool"]
_DT_TO_CODE = {np.dtype(d): i for i, d in enumerate(_DTYPES)}

EV_SEND = 1
EV_BARRIER = 3
EV_COMPLETE = 4


class RpcServer:
    def __init__(self, port=0):
        self._lib = load()
        self._h = self._lib.rpcs_create(int(port))
        if not self._h:
            raise OSError("cannot bind RPC server on port %d" % port)
        self.port = self._lib.rpcs_port(self._h)

    def poll(self):
        """Block for the next inbound event.
        Returns (type, name, array_or_None); type 0 => shutdown."""
        c = ctypes
        name = c.create_string_buffer(1024)
        dtype = c.c_ubyte()
        dims = (c.c_longlong * 16)()
        ndim = c.c_int()
        data = c.c_void_p()
        dlen = c.c_longlong()
        if self._h is None:
            return 0, None, None
        t = self._lib.rpcs_poll(self._h, name, 1024, c.byref(dtype), dims, 16,
                                c.byref(ndim), c.byref(data), c.byref(dlen))
        if t == 0:
            return 0, None, None
        arr = None
        if t == EV_SEND:
            shape = tuple(dims[i] for i in range(ndim.value))
            np_dt = np.dtype(_DTYPES[dtype.value])
            buf = ctypes.string_at(data.value, dlen.value)
            arr = np.frombuffer(buf, dtype=np_dt).reshape(shape).copy()
        # SEND frames may carry a trace context appended to the name
        # (tracing.stamp_wire_name); hand callers the bare name always
        bare, tp = _tr.strip_wire_name(name.value.decode())
        if tp is not None:
            _tr.wire_received(bare, tp)
        return t, bare, arr

    def set_var(self, name, arr):
        # use-after-shutdown must raise, not hand the native layer a NULL
        # handle (a late publisher thread would segfault the process)
        if self._h is None:
            raise ConnectionError("rpc server already shut down")
        arr = np.ascontiguousarray(arr)
        dims = (ctypes.c_longlong * arr.ndim)(*arr.shape)
        self._lib.rpcs_set_var(
            self._h, name.encode(), _DT_TO_CODE[arr.dtype], dims, arr.ndim,
            arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes)

    def serve(self, enable=True):
        if self._h is None:
            raise ConnectionError("rpc server already shut down")
        self._lib.rpcs_serve(self._h, 1 if enable else 0)

    def del_var(self, name):
        if self._h is None:
            raise ConnectionError("rpc server already shut down")
        self._lib.rpcs_del_var(self._h, name.encode())

    def shutdown(self):
        if self._h:
            self._lib.rpcs_destroy(self._h)
            self._h = None


class RpcClient:
    def __init__(self, endpoint, connect_timeout=60.0, rpc_deadline=None,
                 retry_times=None):
        """Retries until the server is up (the reference client's
        wait-for-server behavior; grpc_client.cc connect deadline).

        rpc_deadline: per-REQUEST deadline in seconds; a pserver that hangs
        mid-round raises ConnectionError on the trainer instead of blocking
        forever (reference FLAGS_rpc_deadline + grpc_client.cc deadline
        handling).  None reads FLAGS_rpc_deadline (milliseconds, reference
        units; <=0 disables).  Semantics note: the deadline is enforced as
        a per-syscall IDLE timeout (SO_RCVTIMEO/SO_SNDTIMEO), not an
        elapsed-wall-clock deadline like the reference's gRPC one — a
        server that keeps trickling bytes resets it; a silent one trips it.

        retry_times: bounded reconnect-and-retry on deadline/transport
        failure (reference FLAGS_rpc_retry_times; None reads the flag).
        A failed socket may be mid-frame, so a retry NEVER reuses it:
        the handle is closed and the retry opens a fresh connection after
        an exponential backoff with jitter (backoff_delay).  With
        retry_times=0 the first failure poisons the client (handle
        closed); callers must reconnect with a new RpcClient — the
        pre-retry semantics, still used by tests that assert deadline
        behavior in isolation."""
        import random

        self._lib = load()
        host, port = endpoint.rsplit(":", 1)
        if host in ("localhost", ""):
            host = "127.0.0.1"
        self._host, self._port = host, int(port)
        self.endpoint = endpoint
        self._h = None
        self._rng = random.Random()
        if rpc_deadline is None:
            from .. import flags as _flags

            ms = _flags.get_flags(["FLAGS_rpc_deadline"])[
                "FLAGS_rpc_deadline"]
            rpc_deadline = float(ms) / 1000.0 if ms and ms > 0 else 0.0
        self.rpc_deadline = float(rpc_deadline or 0.0)
        if retry_times is None:
            from .. import flags as _flags

            retry_times = _flags.get_flags(["FLAGS_rpc_retry_times"])[
                "FLAGS_rpc_retry_times"]
        self.retry_times = max(int(retry_times or 0), 0)
        self._connect(connect_timeout)

    def _connect(self, connect_timeout):
        import time

        deadline = time.time() + connect_timeout
        while True:
            self._h = self._lib.rpcc_connect(self._host.encode(), self._port)
            if self._h or time.time() > deadline:
                break
            time.sleep(0.1)
        if not self._h:
            raise ConnectionError("cannot connect to pserver %s within %.0fs"
                                  % (self.endpoint, connect_timeout))
        if self.rpc_deadline > 0:
            self._lib.rpcc_set_deadline(self._h, self.rpc_deadline)

    def _err(self, what):
        hint = (" (deadline %.0fs — pserver hung or connection lost)"
                % self.rpc_deadline if self.rpc_deadline > 0
                else " (connection lost)")
        # a timed-out socket may be mid-frame: reusing this connection
        # would read misaligned frames (silent desync), so every failure
        # closes the handle — retries reconnect fresh
        self.close()
        return ConnectionError("%s to %s failed%s"
                               % (what, self.endpoint, hint))

    def _check_open(self, what):
        if not self._h:
            raise ConnectionError(
                "%s to %s: client closed after a previous deadline/transport "
                "failure — reconnect with a new RpcClient" %
                (what, self.endpoint))

    def _with_retry(self, what, attempt_fn):
        """Run one RPC with up to retry_times reconnect-and-retry rounds.
        Safe for sends because the PS frames are tagged with sequence ids
        and the pserver dedupes replays (distributed/ps.py)."""
        import time

        op = what.split("(", 1)[0]
        last = None
        for i in range(self.retry_times + 1):
            if i:
                _tm.inc("rpc_retry_total", op=op)
                time.sleep(backoff_delay(i - 1, rng=self._rng))
            try:
                if not self._h:
                    # retry_times=0 keeps the poison contract: a closed
                    # client stays closed.  With retries, reconnect —
                    # bounded per attempt so remaining attempts still get
                    # to back off while the server restarts
                    if self.retry_times == 0:
                        self._check_open(what)
                    self._connect(connect_timeout=5.0)
                return attempt_fn()
            except ConnectionError as e:
                last = e
                _tm.inc("rpc_failure_total", op=op)
        _tm.inc("rpc_exhausted_total", op=op)
        raise last

    def send_var(self, name, arr):
        arr = np.ascontiguousarray(arr)
        dims = (ctypes.c_longlong * max(arr.ndim, 1))(*(arr.shape or (0,)))
        what = "send_var(%s)" % name
        # stamp the active trace context onto the frame name — SEND names
        # only surface via server poll (which strips them) and never
        # enter the var store, so GET-by-name semantics are untouched
        wire_name = _tr.stamp_wire_name(name)
        if _tm.enabled():
            _tm.inc("rpc_send_total")
            _tm.inc("rpc_send_bytes_total", int(arr.nbytes))

        def attempt():
            self._check_open(what)
            # fault point rpc.send: "drop" = frame lost before the wire
            # (client sees the same deadline error a lost ACK produces);
            # "error" = transport dies AFTER delivery (ACK lost) — the
            # retry then REPLAYS a frame the server already applied, which
            # is exactly what dedupe-by-sequence must absorb
            kind = maybe_fail("rpc.send")
            if kind == "drop":
                self.close()
                raise FaultInjected("%s to %s: injected frame drop"
                                    % (what, self.endpoint))
            rc = self._lib.rpcc_send_var(
                self._h, wire_name.encode(), _DT_TO_CODE[arr.dtype], dims,
                arr.ndim, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes)
            if rc != 0:
                raise self._err(what)
            if kind == "error":
                self.close()
                raise FaultInjected("%s to %s: injected transport error "
                                    "after delivery" % (what, self.endpoint))

        return self._with_retry(what, attempt)

    def get_var(self, name):
        what = "get_var(%s)" % name
        _tm.inc("rpc_get_total")

        def attempt():
            self._check_open(what)
            kind = maybe_fail("rpc.get")
            if kind == "drop":
                self.close()
                raise FaultInjected("%s to %s: injected request drop"
                                    % (what, self.endpoint))
            c = ctypes
            dtype = c.c_ubyte()
            dims = (c.c_longlong * 16)()
            ndim = c.c_int()
            data = c.c_void_p()
            n = self._lib.rpcc_get_var(self._h, name.encode(), c.byref(dtype),
                                       dims, 16, c.byref(ndim), c.byref(data))
            if n < 0:
                raise self._err(what)
            shape = tuple(dims[i] for i in range(ndim.value))
            buf = ctypes.string_at(data.value, n)
            self._lib.rpc_free(data)
            if kind == "error":
                # reply lost on the way back: discard it and fail (GET is
                # idempotent — the retry simply re-asks)
                self.close()
                raise FaultInjected("%s to %s: injected reply loss"
                                    % (what, self.endpoint))
            return np.frombuffer(buf, dtype=np.dtype(_DTYPES[dtype.value])) \
                .reshape(shape).copy()

        return self._with_retry(what, attempt)

    def barrier(self, kind):
        what = "barrier(%s)" % kind

        def attempt():
            self._check_open(what)
            if self._lib.rpcc_barrier(self._h, kind.encode()) != 0:
                raise self._err(what)

        return self._with_retry(what, attempt)

    def complete(self):
        if not self._h:
            return  # fire-and-forget; tolerate a poisoned/closed client
        self._lib.rpcc_complete(self._h)

    def close(self):
        if self._h:
            self._lib.rpcc_close(self._h)
            self._h = None
