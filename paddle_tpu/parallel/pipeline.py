"""Mesh pipeline parallelism: GPipe over a named mesh axis.

TPU-native replacement for the reference's multi-device pipeline
(framework/pipeline_trainer.cc:24 places sections on distinct devices;
section_worker.cc:141 passes scopes stage->stage through queues).  Here the
"queue" is the ICI: parameters are stage-sharded over a `pp` mesh axis
(stage i's weights live only on pipe-rank-i devices), every device runs the
same SPMD program under shard_map, and activations move stage->stage with
`lax.ppermute` on the classic skewed microbatch schedule:

    tick t:  stage 0 ingests microbatch t; stage s computes the activation
             it received at tick t-1; the last stage emits microbatch
             t-(S-1); then every activation rotates one hop.

The backward pass is NOT hand-scheduled: `jax.grad` through the scan
transposes each ppermute into the reverse rotation, which IS the GPipe
backward schedule (all-forward then all-backward, activations stashed by
the scan) — the compiler owns the bubble, matching how XLA owns collective
scheduling everywhere else in this framework.

Contract: all inter-stage activations share one shape [mb, ...] (the
transformer-block case); embedding/head stay outside the loop via
`embed_fn`/`loss_fn`.  Parameters are passed STACKED with a leading stage
axis sharded over `axis` — `stack_stage_params` builds that layout.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P


def stack_stage_params(per_stage, mesh=None, axis="pp"):
    """[{name: array} per stage] -> {name: [S, ...] array}, placed so the
    stage axis is sharded over the mesh `axis` (each pipe rank holds only
    its own stage's weights)."""
    names = per_stage[0].keys()
    for p in per_stage[1:]:
        if p.keys() != names:
            raise ValueError("stages must share a parameter structure")
    stacked = {n: jnp.stack([jnp.asarray(p[n]) for p in per_stage])
               for n in names}
    if mesh is not None:
        stacked = {
            n: jax.device_put(
                v, NamedSharding(mesh, P(axis, *([None] * (v.ndim - 1)))))
            for n, v in stacked.items()}
    return stacked


def _unstack_local(params):
    """Inside shard_map each pipe rank sees leading stage dim 1."""
    return jax.tree_util.tree_map(lambda v: v[0], params)


def gpipe_spmd(stage_fn, n_stages, n_micro, axis="pp"):
    """Build the SPMD pipeline body (to run under shard_map over `axis`).

    stage_fn(params, h) -> h' applies ONE stage; params is the rank-local
    (unstacked) parameter pytree.  Returns f(params_local, x_micro) ->
    [n_micro, ...] outputs, valid on the LAST pipe rank (garbage
    elsewhere — mask or psum what you consume)."""
    if n_micro < 1:
        raise ValueError("n_micro must be >= 1")

    def forward(params_local, x_micro):
        p = _unstack_local(params_local)
        stage = lax.axis_index(axis)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        buf = jnp.zeros_like(x_micro[0])
        outs = jnp.zeros_like(x_micro)

        def tick(carry, t):
            buf, outs = carry
            idx = jnp.clip(t, 0, n_micro - 1)
            inp = jnp.where(stage == 0, x_micro[idx], buf)
            y = stage_fn(p, inp)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            write = (t >= n_stages - 1) & (stage == n_stages - 1)
            outs = outs.at[out_idx].set(
                jnp.where(write, y, outs[out_idx]))
            buf = lax.ppermute(y, axis, perm)
            return (buf, outs), None

        (buf, outs), _ = lax.scan(
            tick, (buf, outs), jnp.arange(n_micro + n_stages - 1))
        return outs

    return forward


def make_pipeline_step(stage_fn, loss_fn, mesh, n_micro, axis="pp",
                       optimizer=None, embed_fn=None, n_chunks=1,
                       data_axis=None, reduce_grad_axes=()):
    """Jitted stage-sharded GPipe train step.

    stage_fn(params, h) -> h'      one stage (params = that stage's slice)
    loss_fn(outs, labels) -> scalar   computed on last-stage outputs
    embed_fn(x) -> h               optional replicated pre-pipeline embed
    optimizer(p, g) -> p'          optional sgd-style update per leaf
    data_axis                      optional SECOND mesh axis for composed
        data x pipeline parallelism (a real pod job's topology): the
        microbatch dim is sharded over it, params stay replicated across
        it, and gradients/loss are pmean'd over it — loss_fn must be a
        mean over its microbatch outputs so shard means average exactly.

    n_chunks > 1 bounds activation memory: the n_micro microbatches run
    as n_chunks sequential GPipe passes of n_micro/n_chunks each, with
    gradients accumulated between passes (lax.scan) — the jax.grad stash
    holds one CHUNK's activations instead of the whole batch's, at the
    cost of (n_chunks-1) extra pipeline fills.  loss_fn must be a MEAN
    over its microbatch outputs (chunk means are averaged).

    Returns step(params_stacked, x, labels) -> (loss, params_or_grads):
    x [B, ...] is split into n_micro microbatches; loss is replicated; the
    second output is updated params when `optimizer` is given, else grads
    (stage-sharded like the input params).
    """
    n_stages = mesh.shape[axis]
    if n_chunks < 1:
        raise ValueError("n_chunks must be >= 1, got %d" % n_chunks)
    if n_micro % n_chunks:
        raise ValueError("n_micro %d not divisible by n_chunks %d"
                         % (n_micro, n_chunks))
    micro_per_chunk = n_micro // n_chunks
    fwd = gpipe_spmd(stage_fn, n_stages, micro_per_chunk, axis)

    def loss_spmd(params_local, x_micro, labels_micro):
        outs = fwd(params_local, x_micro)
        stage = lax.axis_index(axis)
        raw = loss_fn(outs, labels_micro)
        # LOCAL masked loss (real only on the last pipe rank).  No psum
        # here: under shard_map(check_vma=False) psum transposes to psum,
        # which would scale every cotangent seed by n_stages.  Cross-rank
        # gradient flow still happens through the ppermute transposes —
        # rank s's grads answer d(last rank's loss)/d(stage-s params).
        return jnp.where(stage == n_stages - 1, raw, 0.0)

    def spmd_body(params_local, x_micro, labels_micro):
        if n_chunks == 1:
            loss_local, grads = jax.value_and_grad(loss_spmd)(
                params_local, x_micro, labels_micro)
        else:
            xc = x_micro.reshape((n_chunks, micro_per_chunk)
                                 + x_micro.shape[1:])
            yc = labels_micro.reshape((n_chunks, micro_per_chunk)
                                      + labels_micro.shape[1:])

            def chunk(carry, xy):
                l_acc, g_acc = carry
                l, g = jax.value_and_grad(loss_spmd)(
                    params_local, xy[0], xy[1])
                return (l_acc + l, jax.tree_util.tree_map(
                    jnp.add, g_acc, g)), None

            zeros = jax.tree_util.tree_map(jnp.zeros_like, params_local)
            # derive the accumulator dtype from the actual loss (a
            # hardcoded f32 init would break the scan carry contract
            # under x64 / f64 losses)
            loss_shape = jax.eval_shape(
                loss_spmd, params_local, xc[0], yc[0])
            (loss_sum, grads_sum), _ = lax.scan(
                chunk, (jnp.zeros((), loss_shape.dtype), zeros), (xc, yc))
            # loss_fn is a mean per chunk: average the chunk means/grads
            loss_local = loss_sum / n_chunks
            grads = jax.tree_util.tree_map(lambda g: g / n_chunks,
                                           grads_sum)
        # replicate the loss for reporting OUTSIDE the differentiated path
        loss = lax.psum(lax.stop_gradient(loss_local), axis)
        if data_axis is not None:
            # composed dp: every data shard ran the full pipeline on its
            # slice of each microbatch; average across the data axis
            # (outside the differentiated path, like the loss psum above)
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, data_axis), grads)
            loss = lax.pmean(loss, data_axis)
        for ax in reduce_grad_axes:
            # composed tp inside a stage (3-axis dp x tp x pp): each
            # model rank holds its shard's scatter of the param grads,
            # and because the stage's activations/cotangents are
            # replicated over the axis, every covered element carries an
            # extra axis-size factor (the collective's transpose sums
            # identical per-rank cotangents).  pmean both combines the
            # disjoint shards and cancels that factor — EXACT for stage
            # fns whose params are all consumed in sliced form BEFORE the
            # output collective (column-parallel w AND b, like
            # tests/test_composed_parallelism.py test_three_axis_mesh)
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, ax), grads)
        if optimizer is not None:
            new_params = jax.tree_util.tree_map(optimizer, params_local,
                                                grads)
            return loss, new_params
        return loss, grads

    from ..core.lowering import shard_map_compat

    def step(params_stacked, x, labels):
        for path, v in jax.tree_util.tree_flatten_with_path(
                params_stacked)[0]:
            if v.shape[0] != n_stages:
                # a mismatch would not error downstream: shard_map hands
                # each rank a multi-stage slice and _unstack_local keeps
                # only slice 0, silently training a smaller model
                raise ValueError(
                    "stacked param %s has %d stages but mesh axis %r has "
                    "%d devices" % (jax.tree_util.keystr(path), v.shape[0],
                                    axis, n_stages))
        B = x.shape[0]
        if B % n_micro:
            raise ValueError("batch %d not divisible by n_micro %d"
                             % (B, n_micro))
        mb = B // n_micro
        if data_axis is not None and mb % mesh.shape[data_axis]:
            raise ValueError(
                "microbatch size %d not divisible by data axis %r size %d"
                % (mb, data_axis, mesh.shape[data_axis]))
        x_micro = x.reshape((n_micro, mb) + x.shape[1:])
        if embed_fn is not None:
            x_micro = jax.vmap(embed_fn)(x_micro)
        labels_micro = labels.reshape((n_micro, mb) + labels.shape[1:])
        pspec = jax.tree_util.tree_map(
            lambda v: P(axis, *([None] * (v.ndim - 1))), params_stacked)
        # composed dp x pp: shard the within-microbatch dim over data_axis
        xspec = P(None, data_axis) if data_axis is not None else P()
        body = shard_map_compat(
            spmd_body, mesh,
            in_specs=(pspec, xspec, xspec),
            out_specs=(P(), pspec))
        return body(params_stacked, x_micro, labels_micro)

    return jax.jit(step)


def reference_step(stage_fn, loss_fn, per_stage_params, x, labels,
                   n_micro=1, embed_fn=None):
    """Single-device sequential semantics of the same pipeline (parity
    oracle for tests): run stages back-to-back per microbatch."""
    B = x.shape[0]
    mb = B // n_micro
    x_micro = x.reshape((n_micro, mb) + x.shape[1:])
    if embed_fn is not None:
        x_micro = jax.vmap(embed_fn)(x_micro)
    labels_micro = labels.reshape((n_micro, mb) + labels.shape[1:])

    def full(per_stage):
        outs = []
        for m in range(n_micro):
            h = x_micro[m]
            for p in per_stage:
                h = stage_fn(p, h)
            outs.append(h)
        return loss_fn(jnp.stack(outs), labels_micro)

    loss, grads = jax.value_and_grad(full)(list(per_stage_params))
    return loss, grads
