"""Mixture-of-Experts FFN with expert parallelism (EP) over a mesh axis.

NEW capability vs the reference (SURVEY.md §2.5: expert parallel ABSENT in
the 2019 codebase); the closest reference analog is the pserver-sharded
embedding (parameter_prefetch) — here the "sharded parameter" is the expert
stack and routing is data-dependent.

Design (Mesh-TensorFlow / Switch-style dispatch, XLA-friendly static
shapes):
  - top-k gating with renormalized combine weights
  - fixed expert capacity C = ceil(top_k * T / E * capacity_factor); tokens
    over capacity are dropped (their combine weight is 0) — the standard
    static-shape trade
  - dispatch/combine as einsums over a [T, E, C] one-hot tensor
  - EP: experts sharded over `axis_name`; token blocks exchanged with
    lax.all_to_all before and after the expert FFN (ICI all-to-all), the
    canonical EP schedule.
"""

import math

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["moe_ffn", "gating_dispatch"]


def _capacity(top_k, T, E, factor):
    try:
        return max(int(math.ceil(top_k * T / E * factor)), 1)
    except TypeError:
        # symbolic T during shape inference: capacity is internal only
        # (output stays [T, D]), any positive value works abstractly
        return 1


def gating_dispatch(x, gate_w, num_experts, top_k, capacity):
    """x [T, D] -> (dispatch [T, E, C] float 0/1, combine [T, E, C]),
    plus aux load-balancing loss (Switch-style)."""
    T = x.shape[0]
    E = num_experts
    logits = x @ gate_w                      # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)

    # top-k expert choice per token
    _, topk_idx = lax.top_k(probs, top_k)    # [T, k]
    onehot = jax.nn.one_hot(topk_idx, E, dtype=x.dtype)   # [T, k, E]
    gates = probs[:, None, :] * onehot       # [T, k, E] selected probs
    denom = jnp.sum(gates, axis=(1, 2), keepdims=True)
    gates = gates / jnp.maximum(denom, 1e-9)  # renormalize over chosen k

    # position of each (token, choice) within its expert queue: cumsum over
    # tokens, k-major so choice 0 claims slots first
    flat = onehot.transpose(1, 0, 2).reshape(top_k * T, E)   # [k*T, E]
    pos = jnp.cumsum(flat, axis=0) - flat                     # [k*T, E]
    pos = pos.reshape(top_k, T, E).transpose(1, 0, 2)         # [T, k, E]
    in_cap = pos < capacity
    slot = jnp.where(in_cap, pos, 0).astype(jnp.int32)

    keep = onehot * in_cap.astype(x.dtype)                    # [T, k, E]
    slot_oh = jax.nn.one_hot(slot, capacity, dtype=x.dtype)   # [T, k, E, C]
    dispatch = jnp.einsum("tke,tkec->tec", keep, slot_oh)
    combine = jnp.einsum("tke,tkec->tec", gates * keep, slot_oh)

    # aux loss: fraction of tokens per expert x mean gate prob (Switch eq.4)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(onehot[:, 0, :], axis=0)   # primary-choice load
    aux = jnp.sum(me * ce) * E
    return dispatch, combine, aux


def _expert_ffn(inp, w1, b1, w2, b2):
    """inp [E, C, D]; w1 [E, D, H]; w2 [E, H, D] -> [E, C, D]."""
    h = jnp.einsum("ecd,edh->ech", inp, w1) + b1[:, None, :]
    h = jax.nn.relu(h)
    return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]


def moe_ffn(x, gate_w, w1, b1, w2, b2, top_k=2, capacity_factor=1.25,
            axis_name=None):
    """MoE feed-forward. x [T, D] (flatten batch/seq first); returns
    (out [T, D], aux_loss scalar).

    Without `axis_name`: all experts local.  With `axis_name` (inside
    shard_map): tokens are sharded over the axis, experts too — w1/w2/b*
    are the LOCAL expert shard [E/n, ...]; gate_w is replicated and gating
    runs over the GLOBAL expert count inferred from gate_w's width."""
    D = x.shape[-1]
    if axis_name is None:
        E = w1.shape[0]
        C = _capacity(top_k, x.shape[0], E, capacity_factor)
        dispatch, combine, aux = gating_dispatch(x, gate_w, E, top_k, C)
        expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
        expert_out = _expert_ffn(expert_in, w1, b1, w2, b2)
        out = jnp.einsum("tec,ecd->td", combine, expert_out)
        return out, aux

    n = lax.psum(1, axis_name)
    E_local = w1.shape[0]
    E = E_local * n
    Tl = x.shape[0]                       # local tokens
    # capacity per (expert, source-rank) block
    C = _capacity(top_k, Tl, E, capacity_factor)
    dispatch, combine, aux = gating_dispatch(x, gate_w, E, top_k, C)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)    # [E, C, D]
    # exchange: rank r keeps expert block r; gathers that block from all
    # ranks -> [E_local, n*C, D] local expert batch
    blocks = expert_in.reshape(n, E_local, C, D)
    recv = lax.all_to_all(blocks, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)                     # [n, El, C, D]
    local_in = recv.transpose(1, 0, 2, 3).reshape(E_local, n * C, D)
    local_out = _expert_ffn(local_in, w1, b1, w2, b2)
    back = local_out.reshape(E_local, n, C, D).transpose(1, 0, 2, 3)
    sent = lax.all_to_all(back, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)                     # [n, El, C, D]
    expert_out = sent.reshape(E, C, D)
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out, lax.pmean(aux, axis_name)
