"""Ring attention + Ulysses sequence parallelism (context parallelism).

Both operate on q/k/v laid out [B, H, S, D] with S sharded across a named
mesh axis.  They are written with differentiable collectives (lax.ppermute /
lax.all_to_all), so jax.grad produces the communication-correct backward —
the transpose of a ppermute ring is the reverse ring, which is exactly the
ring-attention backward schedule.

Ring schedule: at step t, rank r holds the K/V chunk originally owned by
rank (r - t) mod P; chunks move to the NEXT rank each step so the exchange
rides neighbor ICI links.  Softmax is accumulated online (same math as
pallas_kernels/flash_attention.py), so each chip never materializes more
than its local [Sq_local, Sk_local] score tile.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "ulysses_attention",
           "make_ring_attention_sharded"]

_NEG_INF = -1e30


def _chunk_attn_update(q, kc, vc, sm_scale, m, l, acc, q_off, k_off, causal):
    """One online-softmax update of (m, l, acc) with a K/V chunk."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   kc.astype(jnp.float32),
                   precision=lax.Precision.HIGHEST) * sm_scale
    if causal:
        Sq, Sk = q.shape[2], kc.shape[2]
        rows = q_off + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        cols = k_off + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where((cols <= rows)[None, None], s, _NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    # fully-masked chunk: m_new stays -inf; keep exp() finite
    m_safe = jnp.where(m_new == _NEG_INF, 0.0, m_new)
    alpha = jnp.exp(jnp.where(m == _NEG_INF, _NEG_INF, m - m_safe))
    p = jnp.exp(s - m_safe)
    if causal:
        p = jnp.where((cols <= rows)[None, None], p, 0.0)
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32),
        precision=lax.Precision.HIGHEST)
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis_name, causal=False, sm_scale=None):
    """Per-shard ring attention; must run inside shard_map/pjit with the
    sequence dimension of q/k/v sharded over `axis_name`.

    q, k, v: [B, H, S_local, D] (the local sequence shard).
    Returns [B, H, S_local, D].
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    P = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    B, H, Sq, D = q.shape
    Sk = k.shape[2]

    m0 = jnp.full((B, H, Sq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sq, 1), jnp.float32)
    acc0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    perm = [(i, (i + 1) % P) for i in range(P)]

    def step(carry, t):
        m, l, acc, kc, vc = carry
        owner = (r - t) % P
        m, l, acc = _chunk_attn_update(
            q, kc, vc, sm_scale, m, l, acc,
            q_off=r * Sq, k_off=owner * Sk, causal=causal)
        # rotate chunks to the next rank (neighbor ICI exchange)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (m, l, acc, kc, vc), None

    # last chunk is peeled out of the scan so the final (dead) rotation —
    # a full K+V neighbor transfer — is never issued
    (m, l, acc, kc, vc), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(P - 1))
    owner_last = (r - (P - 1)) % P
    m, l, acc = _chunk_attn_update(
        q, kc, vc, sm_scale, m, l, acc,
        q_off=r * Sq, k_off=owner_last * Sk, causal=causal)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, causal=False, sm_scale=None,
                      attn_fn=None):
    """DeepSpeed-Ulysses sequence parallelism: all-to-all trades the
    sequence shard for a heads shard, dense attention runs locally over the
    FULL sequence with H/P heads, then the output is swapped back.

    q, k, v: [B, H, S_local, D] with H divisible by the axis size.
    attn_fn(q,k,v,causal,sm_scale): local attention over [B, H/P, S, D];
    defaults to the flash-attention entry (Pallas kernel on TPU).
    """
    P = lax.psum(1, axis_name)

    def seq2head(t):
        # [B, H, S/P, D] -> [B, H/P, S, D]
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def head2seq(t):
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq2head(q), seq2head(k), seq2head(v)
    if attn_fn is None:
        from ..pallas_kernels import flash_attention as _fa

        out = _fa(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    else:
        out = attn_fn(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return head2seq(out)


def make_ring_attention_sharded(mesh, axis_name="sp", causal=False,
                                sm_scale=None, impl="ring"):
    """Build a jittable global-view function: takes FULL [B, H, S, D]
    arrays, shards S over `axis_name` of `mesh`, and runs ring/ulysses
    attention under shard_map.  The convenience entry for model code and
    tests; inside a larger pjit program, call ring_attention directly in
    the shard_map'ed region."""
    from jax.sharding import PartitionSpec as P

    from ..core.lowering import shard_map_compat

    spec = P(None, None, axis_name, None)
    fn = ring_attention if impl == "ring" else ulysses_attention

    def per_shard(q, k, v):
        return fn(q, k, v, axis_name, causal=causal, sm_scale=sm_scale)

    return shard_map_compat(per_shard, mesh, (spec, spec, spec), spec)
