"""Sequence/context parallelism over the device mesh.

NEW capability relative to the reference (SURVEY.md §5: ring attention /
context parallelism are ABSENT in the 2019 codebase — long sequences were
handled only by LoDTensor ragged batching).  Here they are first-class:

- ring_attention: blockwise attention with K/V chunks rotating around the
  mesh axis via lax.ppermute (ICI neighbor exchange), online-softmax
  accumulation, O(S/P) memory per chip.
- ulysses_attention: DeepSpeed-Ulysses-style all-to-all that swaps the
  sequence shard for a heads shard, runs dense local attention (the Pallas
  flash kernel when on TPU), and swaps back.
"""

from .ring_attention import (  # noqa: F401
    ring_attention,
    ulysses_attention,
    make_ring_attention_sharded,
)

from .pipeline import (  # noqa: F401
    gpipe_spmd,
    make_pipeline_step,
    reference_step,
    stack_stage_params,
)
