"""Parameter-server runtime: pserver event loop + trainer comm.

Analog of the reference's PS stack (SURVEY.md §3.4):
- pserver: listen_and_serv_op.cc:110 RunSyncLoop — wait for all trainer
  grad sends, run the optimizer sub-program, publish updated params, repeat;
  exit when every trainer sends COMPLETE (executor.cc:110 SendComplete).
- trainer: send_op/send_barrier/recv_op sequence around each step
  (distribute_transpiler.py's rewritten program), here executed by the
  runtime after the compiled XLA step instead of as graph ops — the compiled
  program stays pure/functional (TPU-idiomatic), communication happens at
  step boundaries over the native C++ transport (native/csrc/tensor_rpc.cc).

Round consistency is VERSION-GATED instead of barrier-gated: round r's
params are published under "name#r" and a GET for that key blocks until the
server finishes round r.  A fast trainer therefore cannot lap the sync
protocol (it blocks in its own round-r GET until every trainer's round-r
grads arrived) — this replaces the reference's fetch_barrier op.

Two consistency modes, selected by the transpiler's sync_mode:
- sync: barrier-gated rounds, mean-aggregated grads (RunSyncLoop).
- async: per-arrival updates with no barriers — each grad immediately runs
  its param's optimizer sub-program and republishes (the reference's
  AsyncCommunicator / RunAsyncLoop, communicator.h:285).  LR-schedule ops
  advance once per logical step (every owned*trainers arrivals), not per
  arrival.

Fault tolerance (this layer owns the at-most-once + liveness contracts; the
transport's retry/backoff lives in native/rpc.py):

- Dedupe-by-sequence: every trainer frame that MUTATES server state (grad
  sends, geo deltas, send-barriers) is tagged ``base@@s<tid>:<nonce>:<seq>``
  with a per-client monotonically increasing seq.  An RPC retry after an
  ACK-lost transport failure replays the frame under the SAME tag, so the
  server applies each logical send at most once (_ReplayFilter).  The nonce
  is drawn fresh per trainer incarnation so a relaunched trainer (seq back
  at 0) is not mistaken for a replay.  Heartbeats/byes stay untagged —
  they are idempotent.
- Eviction / re-quorum (sync mode): the HeartBeatMonitor's checker thread
  EVICTS trainers silent longer than FLAGS_worker_hb_timeout, delivering
  the eviction as a ``__evict__<tid>`` self-RPC so it wakes the round loop
  even when it is parked in poll().  A round's barrier quorum is the LIVE
  set (all - completed - evicted), so rounds keep flowing on survivors.
  Any later contact from an evicted trainer re-admits it.
- Eviction / state reclaim (async mode): the same ``__evict__`` self-RPC
  drops the silent trainer's _ReplayFilter entry and liveness slot, so
  server-side per-trainer state stays bounded by the LIVE trainer set.  A
  relaunched incarnation re-keys under a fresh nonce and its first
  heartbeat re-registers with the monitor — re-admission is automatic.
  Geo mode pushes no heartbeats, so eviction stays disabled there.
- Rejoin: the current round number is published under ``__round__`` and the
  last TWO param versions stay available, so a supervised relaunch
  (distributed/launch.py --restart_failed) can sync its round counter and
  pull a live version despite racing the round it missed.
"""

import collections
import logging

import numpy as np

from ..core import telemetry as _tm
from ..native.rpc import RpcClient, RpcServer, EV_BARRIER, EV_COMPLETE, EV_SEND
from ..utils.fault_injection import maybe_fail

__all__ = ["run_pserver", "TrainerPSComm", "HeartBeatMonitor"]

# pservers running as THREADS of this process (tests; the reference runs
# separate processes).  complete() waits for them to leave the native poll
# so interpreter exit can't abort a daemon thread parked in C++.
_LIVE_SERVERS = set()
_LIVE_LOCK = __import__("threading").Lock()


def _vkey(name, version):
    return "%s#%d" % (name, version)


_HB_PREFIX = "__hb__"
_HB_BYE_PREFIX = "__hb_bye__"
_EVICT_PREFIX = "__evict__"
_ROUND_KEY = "__round__"

_SEQ_SEP = "@@s"


def _untag(name):
    """Split ``base@@s<tid>:<nonce>:<seq>`` -> (base, tid, nonce, seq);
    untagged names come back as (name, None, 0, 0)."""
    i = name.rfind(_SEQ_SEP)
    if i < 0:
        return name, None, 0, 0
    try:
        tid_s, nonce_s, seq_s = name[i + len(_SEQ_SEP):].split(":")
        return name[:i], int(tid_s), int(nonce_s), int(seq_s)
    except ValueError:
        return name, None, 0, 0


class _ReplayFilter:
    """At-most-once filter for tagged trainer frames.  A retry after an
    ACK-lost failure replays the frame under its original tag, and frames
    from one client arrive in send order (sequential client, ordered
    connection), so a frame is a replay iff its seq is <= the last seq seen
    for that (tid, nonce).  A different nonce is a new trainer incarnation:
    accept and re-key."""

    def __init__(self):
        self._last = {}   # tid -> (nonce, last_seq)

    def fresh(self, tid, nonce, seq):
        if tid is None:
            return True
        cur = self._last.get(tid)
        if cur is not None and cur[0] == nonce and seq <= cur[1]:
            return False
        self._last[tid] = (nonce, seq)
        return True

    def evict(self, tid):
        """Forget a trainer's dedupe state (heartbeat eviction): bounds the
        filter to live trainers.  Safe because a relaunched incarnation
        re-keys under a fresh nonce regardless, and the evicted trainer has
        been silent past the heartbeat timeout — far beyond the RPC retry
        budget, so no replayed frame of its old incarnation is in flight."""
        self._last.pop(tid, None)


def _handle_hb(monitor, name):
    """Returns True if `name` was a heartbeat/bye event (consumed)."""
    if name.startswith(_HB_BYE_PREFIX):
        monitor.remove(int(name[len(_HB_BYE_PREFIX):]))
        return True
    if name.startswith(_HB_PREFIX):
        monitor.update(int(name[len(_HB_PREFIX):]))
        return True
    return False


def run_pserver(exe, program, scope):
    """Blocking pserver loop for a transpiled pserver program (the program
    holds one `listen_and_serv` op; metadata on program._ps_server)."""
    from ..core.executor import scope_guard

    meta = program._ps_server
    endpoint = meta["endpoint"]
    port = int(endpoint.rsplit(":", 1)[1])
    params = meta["params"]              # param names owned by this server
    grad_to_param = meta["grad_map"]     # grad name -> param name
    trainers = int(meta["trainers"])
    opt_prog = meta["optimize_program"]

    server = RpcServer(port)
    server.serve(True)
    completed = [0]
    monitor = HeartBeatMonitor(trainers, name="ps:%s" % endpoint)
    # sync mode graduates the monitor from logging to EVICTION: the round
    # loop re-quorums on survivors.  Async mode has no barrier to deadlock,
    # but a dead trainer still pins server state (replay-filter entry +
    # liveness slot), so eviction reclaims those instead.  Geo stays
    # log-only: geo trainers push no heartbeats, so there is no liveness
    # signal to evict on.
    evict_enabled = not meta.get("geo", False)
    # dedicated checker thread (heart_beat_monitor.h runs the monitor in its
    # own thread): a dead trainer in sync mode leaves the server blocked in
    # poll(), so arrival-driven checks alone would never fire.  Evictions
    # are delivered as __evict__ self-RPCs for the same reason — only an
    # inbound event can wake the round loop.
    _mon_stop = __import__("threading").Event()

    def _mon_loop():
        evict_client = [None]
        tick = max(min(monitor.timeout_s / 2.0, 5.0), 0.25)
        while not _mon_stop.wait(tick):
            dead = monitor.check()
            if not dead or not evict_enabled or _mon_stop.is_set():
                continue
            try:
                if evict_client[0] is None:
                    evict_client[0] = RpcClient(
                        "127.0.0.1:%d" % server.port, connect_timeout=5.0,
                        rpc_deadline=5.0, retry_times=0)
                for w in dead:
                    evict_client[0].send_var(_EVICT_PREFIX + str(w),
                                             np.asarray([w], np.int64))
            except Exception:
                # server busy/shutting down — drop the tick, reconnect next
                evict_client[0] = None
        if evict_client[0] is not None:
            evict_client[0].close()

    if not meta.get("geo", False):
        # geo trainers push only sparse param deltas (no heartbeats), so
        # the checker would log false positives there
        __import__("threading").Thread(target=_mon_loop, daemon=True).start()

    def publish(version):
        for p in params:
            server.set_var(
                _vkey(p, version),
                np.asarray(scope.find_var(p).get_tensor().numpy()))
            if version > 1:
                # keep the last TWO versions: a relaunched trainer that just
                # read __round__ == version-1 must still be able to pull it
                # even if this publish races its GETs
                server.del_var(_vkey(p, version - 2))
        # rejoin protocol: relaunched trainers read the round counter to
        # sync TrainerPSComm._round before their first pull
        server.set_var(_ROUND_KEY, np.asarray([version], np.int64))
        # __metrics__ RPC: republish the telemetry snapshot with every
        # round so trainers/tools scrape a fresh view (no-op when off)
        _tm.publish_rpc(server)

    def run_sync():
        import time as _time

        publish(0)  # pserver startup already ran: serve initial params
        version = 0
        replay = _ReplayFilter()
        evicted = set()
        done = set()          # tids that sent __hb_bye__ (clean exit)
        idle_since = [None]   # wall clock when the live set went empty

        def contact(tid):
            """Any frame from a trainer proves liveness and re-admits it."""
            if tid is None or tid in done:
                return
            monitor.update(tid)
            idle_since[0] = None
            if tid in evicted:
                evicted.discard(tid)
                logging.warning("[ps:%s] re-admitted trainer %d",
                                endpoint, tid)
                _tm.inc("ps_readmit_total", ps=endpoint)
                _tm.event("readmit", ps=endpoint, trainer=tid)

        while True:
            t_round = _time.time()
            round_fault = maybe_fail("ps.round")
            if round_fault == "error":
                raise RuntimeError(
                    "injected pserver failure at round %d" % version)
            grads = collections.defaultdict(list)
            barrier_tids = set()
            anon_barriers = [0]   # untagged barriers (raw clients)
            while True:
                live = set(range(trainers)) - done - evicted
                if live and len(barrier_tids & live) + anon_barriers[0] \
                        >= len(live):
                    break
                if not live:
                    # every tracked trainer is done or evicted
                    if completed[0] >= trainers or not evicted:
                        return
                    # supervised relaunch may bring evicted trainers back:
                    # linger for a grace window (woken by the monitor's
                    # periodic __evict__ ticks) before giving up on them
                    now = _time.time()
                    if idle_since[0] is None:
                        idle_since[0] = now
                    elif now - idle_since[0] > 2.0 * monitor.timeout_s:
                        logging.warning(
                            "[ps:%s] all live trainers gone for %.0fs "
                            "(evicted: %s) — shutting down round loop",
                            endpoint, now - idle_since[0], sorted(evicted))
                        return
                t, name, arr = server.poll()
                if t == 0:
                    return
                if t == EV_COMPLETE:
                    completed[0] += 1
                    if completed[0] >= trainers:
                        return
                    continue
                base, tid, nonce, seq = _untag(name)
                if t == EV_BARRIER:
                    if base != "send":
                        continue
                    contact(tid)
                    if not replay.fresh(tid, nonce, seq):
                        _tm.inc("ps_dedupe_drop_total", ps=endpoint)
                        continue
                    if tid is None:
                        anon_barriers[0] += 1
                    else:
                        barrier_tids.add(tid)
                    continue
                if t != EV_SEND:
                    continue
                if base.startswith(_HB_BYE_PREFIX):
                    w = int(base[len(_HB_BYE_PREFIX):])
                    done.add(w)
                    evicted.discard(w)
                    monitor.remove(w)
                    continue
                if base.startswith(_HB_PREFIX):
                    contact(int(base[len(_HB_PREFIX):]))
                    continue
                if base.startswith(_EVICT_PREFIX):
                    w = int(base[len(_EVICT_PREFIX):])
                    if w not in done and w not in evicted:
                        evicted.add(w)
                        logging.warning(
                            "[ps:%s] evicting silent trainer %d — round "
                            "re-quorums on survivors", endpoint, w)
                        _tm.inc("ps_eviction_total", ps=endpoint,
                                mode="sync")
                        _tm.event("eviction", ps=endpoint, trainer=w,
                                  mode="sync", round=version)
                    continue
                contact(tid)
                if not replay.fresh(tid, nonce, seq):
                    _tm.inc("ps_dedupe_drop_total", ps=endpoint)
                    continue
                grads[base].append(arr)
            if round_fault == "drop":
                # injected round drop: lose the round's gradients; params
                # republish unchanged so trainers still make progress
                grads.clear()
            feed = {}
            for gname, parts in grads.items():
                if gname not in grad_to_param:
                    continue
                agg = parts[0].astype(np.float32)
                for p in parts[1:]:
                    agg = agg + p
                feed[gname] = (agg / max(len(parts), 1)).astype(parts[0].dtype)
            if feed:
                with scope_guard(scope):
                    exe.run(opt_prog, feed=feed, fetch_list=[])
            version += 1
            publish(version)
            if _tm.enabled():
                _tm.observe("ps_round_ms", (_time.time() - t_round) * 1e3,
                            ps=endpoint)
                _tm.event("ps_round", ps=endpoint, round=version,
                          grads=len(grads), dropped=round_fault == "drop")

    def run_async():
        """Async mode (reference AsyncCommunicator / RunAsyncLoop,
        communicator.h:285): every grad arrival applies its param's
        optimizer sub-program immediately and republishes — no barriers,
        no versions; trainers always read the freshest params."""
        per_param = meta["optimize_programs"]
        lr_prog = meta.get("lr_program")
        arrivals = [0]
        per_step = max(len(params) * trainers, 1)
        replay = _ReplayFilter()

        def publish_async(p):
            server.set_var(
                _vkey(p, -1),
                np.asarray(scope.find_var(p).get_tensor().numpy()))
            _tm.publish_rpc(server)

        for p in params:
            publish_async(p)
        while True:
            t, name, arr = server.poll()
            if t == 0:
                return
            if t == EV_COMPLETE:
                completed[0] += 1
                if completed[0] >= trainers:
                    return
                continue
            if t != EV_SEND:
                continue
            base, tid, nonce, seq = _untag(name)
            if _handle_hb(monitor, base):
                continue
            if base.startswith(_EVICT_PREFIX):
                # reclaim the silent trainer's server-side state: its
                # replay-filter entry and liveness slot would otherwise
                # live forever.  A relaunched incarnation re-keys under a
                # fresh nonce, and its first heartbeat re-registers with
                # the monitor, so re-admission is automatic.
                w = int(base[len(_EVICT_PREFIX):])
                replay.evict(w)
                monitor.remove(w)
                logging.warning(
                    "[ps:%s] evicted silent trainer %d (async) — "
                    "replay/liveness state reclaimed", endpoint, w)
                _tm.inc("ps_eviction_total", ps=endpoint, mode="async")
                _tm.event("eviction", ps=endpoint, trainer=w, mode="async")
                continue
            if base in grad_to_param:
                if not replay.fresh(tid, nonce, seq):
                    # replayed send: already applied this grad
                    _tm.inc("ps_dedupe_drop_total", ps=endpoint)
                    continue
                pname = grad_to_param[base]
                with scope_guard(scope):
                    exe.run(per_param[pname], feed={base: arr},
                            fetch_list=[])
                    arrivals[0] += 1
                    if (lr_prog is not None
                            and lr_prog.global_block().ops
                            and arrivals[0] % per_step == 0):
                        exe.run(lr_prog, fetch_list=[])
                publish_async(pname)

    def run_geo():
        """Geo-SGD (reference geo_sgd_transpiler.py + GeoSgdCommunicator,
        communicator.h:332): trainers optimize locally and push param
        DELTAS; the server adds each delta to its copy and republishes —
        no optimizer runs server-side.  Deltas are NOT idempotent (the
        server accumulates them), so dedupe matters doubly here."""
        replay = _ReplayFilter()

        def publish_geo(p):
            server.set_var(
                _vkey(p, -1),
                np.asarray(scope.find_var(p).get_tensor().numpy()))
            _tm.publish_rpc(server)

        for p in params:
            publish_geo(p)
        param_set = set(params)
        while True:
            t, name, arr = server.poll()
            if t == 0:
                return
            if t == EV_COMPLETE:
                completed[0] += 1
                if completed[0] >= trainers:
                    return
                continue
            if t != EV_SEND:
                continue
            base, tid, nonce, seq = _untag(name)
            if base in param_set:
                if not replay.fresh(tid, nonce, seq):
                    # replayed delta would double-apply
                    _tm.inc("ps_dedupe_drop_total", ps=endpoint)
                    continue
                cur = np.asarray(scope.find_var(base).get_tensor().numpy())
                scope.var(base).set(cur + arr)
                publish_geo(base)

    with _LIVE_LOCK:
        _LIVE_SERVERS.add(id(server))
    try:
        if meta.get("geo", False):
            run_geo()
        elif meta.get("sync", True):
            run_sync()
        else:
            run_async()
    finally:
        _mon_stop.set()
        server.shutdown()
        with _LIVE_LOCK:
            _LIVE_SERVERS.discard(id(server))


class TrainerPSComm:
    """Per-trainer connections to every pserver + the sync-step protocol."""

    def __init__(self, meta):
        import random

        self.meta = meta
        self.endpoints = meta["endpoints"]
        self.param_to_ep = meta["param_to_ep"]
        self.param_to_grad = meta["param_grad"]
        self.trainer_id = int(meta["trainer_id"])
        self.sync = bool(meta.get("sync", True))
        self.geo = bool(meta.get("geo", False))
        self.geo_push_nums = int(meta.get("geo_push_nums", 100))
        self._clients = {ep: RpcClient(ep) for ep in self.endpoints}
        self._round = 0
        self._step_count = 0
        self._snapshot = {}   # geo: param values at the last push/pull
        self._closed = False
        # dedupe-by-sequence tag state: nonce identifies this incarnation
        # (a relaunched trainer must not look like a replay of its previous
        # life), seq orders this incarnation's state-mutating frames
        self._nonce = random.getrandbits(31)
        self._seq = 0

    def _tag(self, base):
        s = self._seq
        self._seq += 1
        return "%s%s%d:%d:%d" % (base, _SEQ_SEP, self.trainer_id,
                                 self._nonce, s)

    def _pull(self, scope, version):
        for p, ep in self.param_to_ep.items():
            scope.var(p).set(self._clients[ep].get_var(_vkey(p, version)))

    # initial param pull (reference: recv ops in the rewritten startup)
    def pull_initial_params(self, scope):
        if self.sync and not self.geo:
            # rejoin protocol: a relaunched trainer joins at the cluster's
            # CURRENT round, not 0.  Servers publish __round__ with every
            # version; they stay within one round of each other (lockstep),
            # and the laggard completes its in-flight round on the
            # survivors' quorum, so max() is always pullable (servers keep
            # the last two versions).
            self._round = max(
                int(self._clients[ep].get_var(_ROUND_KEY).ravel()[0])
                for ep in self.endpoints)
            self._pull(scope, self._round)
        else:
            self._pull(scope, -1)
        if self.geo:
            self._snapshot = {
                p: np.asarray(scope.find_var(p).get_tensor().numpy()).copy()
                for p in self.param_to_ep}

    def step(self, scope, grad_values):
        """grad_values: grad name -> ndarray for THIS trainer's step."""
        if self.geo:
            return self._geo_step(scope)
        if self._closed:
            raise RuntimeError(
                "PS trainer already completed (Executor.close() was called); "
                "create a new scope/executor to train again")
        # heartbeat: one tiny var per step so the server's HeartBeatMonitor
        # tracks this worker's liveness (heart_beat_monitor.h UPDATE mode).
        # Untagged: heartbeats are idempotent, replays are harmless.
        hb = np.asarray([self.trainer_id], np.int64)
        for c in self._clients.values():
            c.send_var(_HB_PREFIX + str(self.trainer_id), hb)
        for p, g in self.param_to_grad.items():
            if g in grad_values:
                self._clients[self.param_to_ep[p]].send_var(
                    self._tag(g), grad_values[g])
        if not self.sync:
            # async (communicator.h:285): no barrier, read freshest params
            self._pull(scope, -1)
            return
        for c in self._clients.values():
            c.barrier(self._tag("send"))
        self._round += 1
        self._pull(scope, self._round)  # blocks until every trainer's round
        # arrived and the optimizer ran — the sync point

    def _geo_step(self, scope):
        """Local training; every K steps push param deltas vs the last
        snapshot and pull the server's merged params."""
        if self._closed:
            raise RuntimeError("PS trainer already completed")
        self._step_count += 1
        if self._step_count % self.geo_push_nums:
            return
        for p, ep in self.param_to_ep.items():
            cur = np.asarray(scope.find_var(p).get_tensor().numpy())
            delta = cur - self._snapshot[p]
            self._clients[ep].send_var(self._tag(p), delta)
        self._pull(scope, -1)
        for p in self.param_to_ep:
            self._snapshot[p] = np.asarray(
                scope.find_var(p).get_tensor().numpy()).copy()

    def complete(self):
        if self._closed:
            return
        self._closed = True
        bye = np.asarray([self.trainer_id], np.int64)
        for c in self._clients.values():
            try:
                c.send_var(_HB_BYE_PREFIX + str(self.trainer_id), bye)
                c.complete()
                c.close()
            except Exception:
                pass
        # wait (bounded) for IN-PROCESS pserver threads to leave the
        # native poll: a daemon thread parked in C++ at interpreter exit
        # trips CPython's pthread_exit unwinding (abort).  Costs nothing
        # when pservers run as separate processes (registry empty); with
        # several trainer threads only the last COMPLETE releases the
        # servers, so earlier completers may wait out the bound.
        import time

        deadline = time.time() + 2.0
        while time.time() < deadline:
            with _LIVE_LOCK:
                if not _LIVE_SERVERS:
                    return
            time.sleep(0.01)


class HeartBeatMonitor:
    """Pserver-side worker liveness tracking (parity:
    operators/distributed/heart_beat_monitor.h:54): records each worker's
    last-contact timestamp; `check` returns (and logs once) workers silent
    for longer than `timeout_s`.  The reference runs this only in UPDATE
    mode and only LOGS; here run_pserver's checker thread turns the dead
    list into sync-quorum EVICTIONS (see module docstring) — the monitor
    itself stays a passive bookkeeper.

    timeout_s=None reads FLAGS_worker_hb_timeout.  Workers are seeded at
    construction + startup_grace_s (default: one extra timeout) so a
    worker that dies before its first heartbeat IS eventually caught, but
    a slow start (process spawn + jax import can take tens of seconds)
    is not mistaken for death."""

    def __init__(self, n_workers, timeout_s=None, name="ps",
                 startup_grace_s=None, worker_ids=None):
        import time

        if timeout_s is None:
            from .. import flags as _flags

            timeout_s = float(_flags.flag("worker_hb_timeout") or 60.0)
        self._time = time.time
        if worker_ids is None:
            worker_ids = range(n_workers)
        worker_ids = [int(w) for w in worker_ids]
        self.n_workers = len(worker_ids)
        self.timeout_s = timeout_s
        self.startup_grace_s = (timeout_s if startup_grace_s is None
                                else startup_grace_s)
        self.name = name
        now = self._time()
        self._last_seen = {w: now + self.startup_grace_s
                           for w in worker_ids}
        self._warned = set()
        self._lock = __import__("threading").Lock()

    def update(self, worker_id):
        with self._lock:
            self._last_seen[int(worker_id)] = self._time()
            self._warned.discard(int(worker_id))

    def remove(self, worker_id):
        """Worker exited cleanly (SendComplete) — stop tracking it."""
        with self._lock:
            self._last_seen.pop(int(worker_id), None)
            self._warned.discard(int(worker_id))

    def check(self):
        """Returns the list of currently-dead worker ids (and logs new
        ones once, like the monitor thread's LOG(WARNING))."""
        now = self._time()
        with self._lock:
            dead = [(w, now - t) for w, t in self._last_seen.items()
                    if now - t > self.timeout_s]
            fresh = [wt for wt in dead if wt[0] not in self._warned]
            self._warned.update(w for w, _ in fresh)
        _tm.set_gauge("ps_dead_workers", len(dead), ps=self.name)
        if fresh:
            _tm.inc("ps_heartbeat_miss_total", len(fresh), ps=self.name)
        for w, silent in fresh:
            logging.warning("[%s] worker %d silent for %.0fs",
                            self.name, w, silent)
        return [w for w, _ in dead]
