"""Parameter-server runtime: pserver event loop + trainer comm.

Analog of the reference's PS stack (SURVEY.md §3.4):
- pserver: listen_and_serv_op.cc:110 RunSyncLoop — wait for all trainer
  grad sends, run the optimizer sub-program, publish updated params, repeat;
  exit when every trainer sends COMPLETE (executor.cc:110 SendComplete).
- trainer: send_op/send_barrier/recv_op sequence around each step
  (distribute_transpiler.py's rewritten program), here executed by the
  runtime after the compiled XLA step instead of as graph ops — the compiled
  program stays pure/functional (TPU-idiomatic), communication happens at
  step boundaries over the native C++ transport (native/csrc/tensor_rpc.cc).

Round consistency is VERSION-GATED instead of barrier-gated: round r's
params are published under "name#r" and a GET for that key blocks until the
server finishes round r.  A fast trainer therefore cannot lap the sync
protocol (it blocks in its own round-r GET until every trainer's round-r
grads arrived) — this replaces the reference's fetch_barrier op.

Two consistency modes, selected by the transpiler's sync_mode:
- sync: barrier-gated rounds, mean-aggregated grads (RunSyncLoop).
- async: per-arrival updates with no barriers — each grad immediately runs
  its param's optimizer sub-program and republishes (the reference's
  AsyncCommunicator / RunAsyncLoop, communicator.h:285).  LR-schedule ops
  advance once per logical step (every owned*trainers arrivals), not per
  arrival.
"""

import collections

import numpy as np

from ..native.rpc import RpcClient, RpcServer, EV_BARRIER, EV_COMPLETE, EV_SEND

__all__ = ["run_pserver", "TrainerPSComm", "HeartBeatMonitor"]

# pservers running as THREADS of this process (tests; the reference runs
# separate processes).  complete() waits for them to leave the native poll
# so interpreter exit can't abort a daemon thread parked in C++.
_LIVE_SERVERS = set()
_LIVE_LOCK = __import__("threading").Lock()


def _vkey(name, version):
    return "%s#%d" % (name, version)


_HB_PREFIX = "__hb__"
_HB_BYE_PREFIX = "__hb_bye__"


def _handle_hb(monitor, name):
    """Returns True if `name` was a heartbeat/bye event (consumed)."""
    if name.startswith(_HB_BYE_PREFIX):
        monitor.remove(int(name[len(_HB_BYE_PREFIX):]))
        return True
    if name.startswith(_HB_PREFIX):
        monitor.update(int(name[len(_HB_PREFIX):]))
        return True
    return False


def run_pserver(exe, program, scope):
    """Blocking pserver loop for a transpiled pserver program (the program
    holds one `listen_and_serv` op; metadata on program._ps_server)."""
    from ..core.executor import scope_guard

    meta = program._ps_server
    endpoint = meta["endpoint"]
    port = int(endpoint.rsplit(":", 1)[1])
    params = meta["params"]              # param names owned by this server
    grad_to_param = meta["grad_map"]     # grad name -> param name
    trainers = int(meta["trainers"])
    opt_prog = meta["optimize_program"]

    server = RpcServer(port)
    server.serve(True)
    completed = [0]
    monitor = HeartBeatMonitor(trainers, name="ps:%s" % endpoint)
    # dedicated checker thread (heart_beat_monitor.h runs the monitor in its
    # own thread): a dead trainer in sync mode leaves the server blocked in
    # poll(), so arrival-driven checks alone would never fire
    _mon_stop = __import__("threading").Event()

    def _mon_loop():
        while not _mon_stop.wait(max(monitor.timeout_s / 2, 0.5)):
            monitor.check()

    if not meta.get("geo", False):
        # geo trainers push only sparse param deltas (no heartbeats), so
        # the checker would log false positives there
        __import__("threading").Thread(target=_mon_loop, daemon=True).start()

    def publish(version):
        for p in params:
            server.set_var(
                _vkey(p, version),
                np.asarray(scope.find_var(p).get_tensor().numpy()))
            if version > 0:
                server.del_var(_vkey(p, version - 1))

    def collect_round(grads):
        """Consume events until every LIVE trainer's send-barrier arrives;
        SEND events land in grad buckets.  A COMPLETE decrements the round
        fanin (the reference decrements the barrier counter on SendComplete
        so stragglers don't deadlock).  False => all trainers done."""
        seen = 0
        while seen < trainers - completed[0]:
            t, name, arr = server.poll()
            if t == 0:
                return False
            if t == EV_COMPLETE:
                completed[0] += 1
                if completed[0] >= trainers:
                    return False
            elif t == EV_BARRIER and name == "send":
                seen += 1
            elif t == EV_SEND:
                if not _handle_hb(monitor, name):
                    grads[name].append(arr)
        return True

    def run_sync():
        publish(0)  # pserver startup already ran: serve initial params
        version = 0
        while True:
            grads = collections.defaultdict(list)
            if not collect_round(grads):
                return
            feed = {}
            for gname, parts in grads.items():
                if gname not in grad_to_param:
                    continue
                agg = parts[0].astype(np.float32)
                for p in parts[1:]:
                    agg = agg + p
                feed[gname] = (agg / max(len(parts), 1)).astype(parts[0].dtype)
            with scope_guard(scope):
                exe.run(opt_prog, feed=feed, fetch_list=[])
            version += 1
            publish(version)

    def run_async():
        """Async mode (reference AsyncCommunicator / RunAsyncLoop,
        communicator.h:285): every grad arrival applies its param's
        optimizer sub-program immediately and republishes — no barriers,
        no versions; trainers always read the freshest params."""
        per_param = meta["optimize_programs"]
        lr_prog = meta.get("lr_program")
        arrivals = [0]
        per_step = max(len(params) * trainers, 1)

        def publish_async(p):
            server.set_var(
                _vkey(p, -1),
                np.asarray(scope.find_var(p).get_tensor().numpy()))

        for p in params:
            publish_async(p)
        while True:
            t, name, arr = server.poll()
            if t == 0:
                return
            if t == EV_COMPLETE:
                completed[0] += 1
                if completed[0] >= trainers:
                    return
            elif t == EV_SEND and _handle_hb(monitor, name):
                pass
            elif t == EV_SEND and name in grad_to_param:
                pname = grad_to_param[name]
                with scope_guard(scope):
                    exe.run(per_param[pname], feed={name: arr},
                            fetch_list=[])
                    arrivals[0] += 1
                    if (lr_prog is not None
                            and lr_prog.global_block().ops
                            and arrivals[0] % per_step == 0):
                        exe.run(lr_prog, fetch_list=[])
                publish_async(pname)

    def run_geo():
        """Geo-SGD (reference geo_sgd_transpiler.py + GeoSgdCommunicator,
        communicator.h:332): trainers optimize locally and push param
        DELTAS; the server adds each delta to its copy and republishes —
        no optimizer runs server-side."""
        def publish_geo(p):
            server.set_var(
                _vkey(p, -1),
                np.asarray(scope.find_var(p).get_tensor().numpy()))

        for p in params:
            publish_geo(p)
        param_set = set(params)
        while True:
            t, name, arr = server.poll()
            if t == 0:
                return
            if t == EV_COMPLETE:
                completed[0] += 1
                if completed[0] >= trainers:
                    return
            elif t == EV_SEND and name in param_set:
                cur = np.asarray(scope.find_var(name).get_tensor().numpy())
                scope.var(name).set(cur + arr)
                publish_geo(name)

    with _LIVE_LOCK:
        _LIVE_SERVERS.add(id(server))
    try:
        if meta.get("geo", False):
            run_geo()
        elif meta.get("sync", True):
            run_sync()
        else:
            run_async()
    finally:
        _mon_stop.set()
        server.shutdown()
        with _LIVE_LOCK:
            _LIVE_SERVERS.discard(id(server))


class TrainerPSComm:
    """Per-trainer connections to every pserver + the sync-step protocol."""

    def __init__(self, meta):
        self.meta = meta
        self.endpoints = meta["endpoints"]
        self.param_to_ep = meta["param_to_ep"]
        self.param_to_grad = meta["param_grad"]
        self.trainer_id = int(meta["trainer_id"])
        self.sync = bool(meta.get("sync", True))
        self.geo = bool(meta.get("geo", False))
        self.geo_push_nums = int(meta.get("geo_push_nums", 100))
        self._clients = {ep: RpcClient(ep) for ep in self.endpoints}
        self._round = 0
        self._step_count = 0
        self._snapshot = {}   # geo: param values at the last push/pull
        self._closed = False

    def _pull(self, scope, version):
        for p, ep in self.param_to_ep.items():
            scope.var(p).set(self._clients[ep].get_var(_vkey(p, version)))

    # initial param pull (reference: recv ops in the rewritten startup)
    def pull_initial_params(self, scope):
        self._pull(scope, 0 if (self.sync and not self.geo) else -1)
        if self.geo:
            self._snapshot = {
                p: np.asarray(scope.find_var(p).get_tensor().numpy()).copy()
                for p in self.param_to_ep}

    def step(self, scope, grad_values):
        """grad_values: grad name -> ndarray for THIS trainer's step."""
        if self.geo:
            return self._geo_step(scope)
        if self._closed:
            raise RuntimeError(
                "PS trainer already completed (Executor.close() was called); "
                "create a new scope/executor to train again")
        # heartbeat: one tiny var per step so the server's HeartBeatMonitor
        # tracks this worker's liveness (heart_beat_monitor.h UPDATE mode)
        hb = np.asarray([self.trainer_id], np.int64)
        for c in self._clients.values():
            c.send_var(_HB_PREFIX + str(self.trainer_id), hb)
        for p, g in self.param_to_grad.items():
            if g in grad_values:
                self._clients[self.param_to_ep[p]].send_var(g, grad_values[g])
        if not self.sync:
            # async (communicator.h:285): no barrier, read freshest params
            self._pull(scope, -1)
            return
        for c in self._clients.values():
            c.barrier("send")
        self._round += 1
        self._pull(scope, self._round)  # blocks until every trainer's round
        # arrived and the optimizer ran — the sync point

    def _geo_step(self, scope):
        """Local training; every K steps push param deltas vs the last
        snapshot and pull the server's merged params."""
        if self._closed:
            raise RuntimeError("PS trainer already completed")
        self._step_count += 1
        if self._step_count % self.geo_push_nums:
            return
        for p, ep in self.param_to_ep.items():
            cur = np.asarray(scope.find_var(p).get_tensor().numpy())
            delta = cur - self._snapshot[p]
            self._clients[ep].send_var(p, delta)
        self._pull(scope, -1)
        for p in self.param_to_ep:
            self._snapshot[p] = np.asarray(
                scope.find_var(p).get_tensor().numpy()).copy()

    def complete(self):
        if self._closed:
            return
        self._closed = True
        bye = np.asarray([self.trainer_id], np.int64)
        for c in self._clients.values():
            try:
                c.send_var(_HB_BYE_PREFIX + str(self.trainer_id), bye)
                c.complete()
                c.close()
            except Exception:
                pass
        # wait (bounded) for IN-PROCESS pserver threads to leave the
        # native poll: a daemon thread parked in C++ at interpreter exit
        # trips CPython's pthread_exit unwinding (abort).  Costs nothing
        # when pservers run as separate processes (registry empty); with
        # several trainer threads only the last COMPLETE releases the
        # servers, so earlier completers may wait out the bound.
        import time

        deadline = time.time() + 2.0
        while time.time() < deadline:
            with _LIVE_LOCK:
                if not _LIVE_SERVERS:
                    return
            time.sleep(0.01)


class HeartBeatMonitor:
    """Pserver-side worker liveness tracking (parity:
    operators/distributed/heart_beat_monitor.h:54): records each worker's
    last-contact timestamp; `check` logs workers silent for longer than
    `timeout_s`.  The reference runs this only in UPDATE mode and only
    logs — no eviction — and so do we."""

    def __init__(self, n_workers, timeout_s=60.0, name="ps"):
        import time

        self._time = time.time
        self.n_workers = n_workers
        self.timeout_s = timeout_s
        self.name = name
        # seed every worker at construction (heart_beat_monitor.h does the
        # same) so a worker that dies before its first heartbeat is caught
        now = self._time()
        self._last_seen = {w: now for w in range(n_workers)}
        self._warned = set()
        self._lock = __import__("threading").Lock()

    def update(self, worker_id):
        with self._lock:
            self._last_seen[int(worker_id)] = self._time()
            self._warned.discard(int(worker_id))

    def remove(self, worker_id):
        """Worker exited cleanly (SendComplete) — stop tracking it."""
        with self._lock:
            self._last_seen.pop(int(worker_id), None)
            self._warned.discard(int(worker_id))

    def check(self):
        """Returns the list of currently-dead worker ids (and logs new
        ones once, like the monitor thread's LOG(WARNING))."""
        import logging

        now = self._time()
        with self._lock:
            dead = [(w, now - t) for w, t in self._last_seen.items()
                    if now - t > self.timeout_s]
            fresh = [wt for wt in dead if wt[0] not in self._warned]
            self._warned.update(w for w, _ in fresh)
        for w, silent in fresh:
            logging.warning("[%s] worker %d silent for %.0fs",
                            self.name, w, silent)
        return [w for w, _ in dead]
