"""Distributed runtime: process launcher + multi-host bootstrap.

The reference's NCCL data plane is replaced by XLA collectives over the
device mesh; the control plane (who talks to whom) keeps the reference's
env-var scheme so launch scripts port unchanged.
"""

from . import launch  # noqa: F401
