"""Large-scale sparse parameter server — PSLib/Downpour analog.

Parity target (SURVEY.md §2.5 "Large-scale sparse PS"): the reference hosts
huge embedding tables on pserver-side sparse tables; DownpourWorker pulls the
rows its batch touches before the op loop and pushes per-row grads after
(framework/fleet/fleet_wrapper.h:55-150, downpour_worker.cc).  The dense
network never materializes the full table.

TPU-native shape of the same idea: the compiled XLA step stays pure — it
computes on a small [U, D] matrix of *pulled rows* fed like data, with batch
ids remapped to [0, U).  The runtime does pull (RPC gather) before the step
and push (per-row grad scatter + server-side SGD/Adagrad) after, over the
same native C++ tensor transport the dense PS uses
(native/csrc/tensor_rpc.cc).

Sharding: rows are routed to servers by ``id % num_servers`` (the
reference's RoundRobin ps_dispatcher over row sections).

Protocol (all vars namespaced by table name):
  client->server  SEND  "<tbl>.pull_ids@<client>#<seq>"   int64 [K]
  server->client  GET   "<tbl>.rows@<client>#<seq>"       float [K, D]
  client->server  SEND  "<tbl>.push_ids@<client>#<seq>" + ".push_grads@..."
COMPLETE shuts the server down (fleet.stop_worker analog).
"""

import collections
import threading

import numpy as np

from ..native.rpc import RpcClient, RpcServer, EV_COMPLETE, EV_SEND

__all__ = ["SparseTableServer", "SparseTableClient", "DistributedEmbedding"]


class SparseTableServer:
    """One shard of a sparse embedding table + its optimizer state.

    Rows are lazily initialized on first touch (uniform [-scale, scale]) —
    PSLib tables do the same so the full vocab never has to be allocated
    up front.  Supported optimizers: sgd, adagrad (DownpourSparseTable's
    default rule)."""

    def __init__(self, port, dim, optimizer="adagrad", lr=0.05,
                 init_scale=0.01, seed=0):
        self.server = RpcServer(port)
        self.port = self.server.port
        self.dim = dim
        self.lr = lr
        self.optimizer = optimizer
        self.init_scale = init_scale
        self.rows = {}            # global id -> np[D]
        self.g2sum = {}           # adagrad accumulator
        self.rng = np.random.RandomState(seed)
        self._thread = None

    # -- row access -----------------------------------------------------------

    def _row(self, gid):
        r = self.rows.get(gid)
        if r is None:
            r = self.rng.uniform(-self.init_scale, self.init_scale,
                                 self.dim).astype(np.float32)
            self.rows[gid] = r
        return r

    def _update(self, gid, grad):
        r = self._row(gid)
        if self.optimizer == "adagrad":
            acc = self.g2sum.get(gid, 0.0) + float(np.sum(grad * grad))
            self.g2sum[gid] = acc
            r -= self.lr / np.sqrt(acc + 1e-10) * grad
        else:  # sgd
            r -= self.lr * grad

    # -- event loop -----------------------------------------------------------

    def run(self):
        """Blocking poll loop; returns after COMPLETE or shutdown."""
        self.server.serve(True)
        pending_push = {}
        last_rows_var = {}   # client tag prefix -> last published var name
        while True:
            t, name, arr = self.server.poll()
            if t == 0 or t == EV_COMPLETE:
                return
            if t != EV_SEND:
                continue
            tbl, rest = name.split(".", 1)
            kind, tag = rest.split("@", 1)
            if kind == "pull_ids":
                ids = arr.astype(np.int64).reshape(-1)
                out = np.stack([self._row(int(g)) for g in ids]) \
                    if len(ids) else np.zeros((0, self.dim), np.float32)
                var = "%s.rows@%s" % (tbl, tag)
                self.server.set_var(var, out)
                # GC the previous pull's published rows for this client —
                # pulls are sequential per client, so seq-1 was consumed
                # before seq was requested (cf. dense PS version GC,
                # distributed/ps.py publish())
                client = tag.split("#", 1)[0]
                prev = last_rows_var.get((tbl, client))
                if prev is not None and prev != var:
                    self.server.del_var(prev)
                last_rows_var[(tbl, client)] = var
            elif kind == "push_ids":
                if len(pending_push) > 1024:
                    pending_push.pop(next(iter(pending_push)))  # orphan cap
                pending_push[tag] = arr.astype(np.int64).reshape(-1)
            elif kind == "push_grads":
                ids = pending_push.pop(tag, None)
                if ids is not None:
                    g = arr.reshape(len(ids), self.dim)
                    for i, gid in enumerate(ids):
                        self._update(int(gid), g[i])

    def start_thread(self):
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self._thread

    def shutdown(self):
        self.server.shutdown()


class SparseTableClient:
    """Trainer-side pull/push routing ids to shards by id % n_servers
    (FleetWrapper::PullSparseVarsSync / PushSparseVarsAsync analog)."""

    _instance_counter = __import__("itertools").count()

    def __init__(self, table, endpoints, client_id=None):
        import os

        self.table = table
        self.clients = [RpcClient(ep) for ep in endpoints]
        self.n = len(endpoints)
        # default id is unique across processes (pid) AND across instances
        # within one process (counter) so pull/push tags never collide
        if client_id is None:
            client_id = "%d-%d" % (os.getpid(),
                                   next(SparseTableClient._instance_counter))
        self.client_id = client_id
        self._seq = 0

    def pull(self, ids):
        """ids: int array of global row ids -> rows [len(ids), D] in order."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        self._seq += 1
        tag = "%s#%d" % (self.client_id, self._seq)
        per = [ids[ids % self.n == s] for s in range(self.n)]
        for s, cl in enumerate(self.clients):
            cl.send_var("%s.pull_ids@%s" % (self.table, tag), per[s])
        out = None
        for s, cl in enumerate(self.clients):
            rows = cl.get_var("%s.rows@%s" % (self.table, tag))
            if out is None:
                out = np.zeros((len(ids), rows.shape[1]), np.float32)
            pos = np.nonzero(ids % self.n == s)[0]
            out[pos] = rows
        return out

    def push(self, ids, grads):
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), -1)
        self._seq += 1
        tag = "%s#%d" % (self.client_id, self._seq)
        for s, cl in enumerate(self.clients):
            m = ids % self.n == s
            cl.send_var("%s.push_ids@%s" % (self.table, tag), ids[m])
            cl.send_var("%s.push_grads@%s" % (self.table, tag), grads[m])

    def complete(self):
        for cl in self.clients:
            cl.complete()

    def close(self):
        for cl in self.clients:
            cl.close()


class DistributedEmbedding:
    """Program wiring for a PS-hosted embedding (DownpourWorker flow).

    Build phase (inside program_guard)::

        demb = DistributedEmbedding("emb_tbl", dim=16)
        out = demb.lookup(ids_var, batch_ids_max=64)   # [B, D] variable
        ... rest of the network; loss.minimize(...)

    Run phase, per step (ids = numpy [B] int64)::

        feed, info = demb.prepare_feed(ids)            # pulls rows via RPC
        outs = exe.run(main, feed={**data_feed, **feed},
                       fetch_list=[loss, demb.grad_var(main)])
        demb.push_grads(info, outs[-1])                # pushes row grads

    The step computes with the pulled [U, D] rows only; the full table
    lives on the sparse servers."""

    def __init__(self, table, dim, client=None):
        self.table = table
        self.dim = dim
        self.client = client
        self.rows_name = table + "@rows"
        self.local_ids_name = table + "@local_ids"
        self.max_rows = None

    def lookup(self, ids_var, batch_ids_max):
        """batch_ids_max: static upper bound on unique ids per batch (rows
        are zero-padded to it so the compiled step keeps one shape)."""
        import paddle_tpu as fluid

        self.max_rows = batch_ids_max
        rows = fluid.layers.data(self.rows_name,
                                 shape=[batch_ids_max, self.dim],
                                 append_batch_size=False,
                                 stop_gradient=False)
        local = fluid.layers.data(self.local_ids_name, shape=[],
                                  dtype="int64")  # [B] batch-sized
        out = fluid.layers.gather(rows, local)
        return out

    def lookup_bag(self, batch_size, bag_size, batch_ids_max):
        """Bagged (multi-hot) lookup: each sample carries up to `bag_size`
        feature ids; the step computes Out[b] = sum of that sample's rows —
        the recommender read pattern.  Emits ONE `embedding_bag` op over
        the pulled [batch_ids_max, D] rows with [B, K] local ids (-1 pads
        ragged bags), which routes to the block-sparse Pallas gather/sum
        kernel under FLAGS_use_pallas_embedding_bag (probe-gated,
        pallas_kernels/adoption.py) and to the masked take+sum composition
        otherwise.  Feed with prepare_feed_bags()."""
        import paddle_tpu as fluid
        from ..layer_helper import LayerHelper

        self.max_rows = batch_ids_max
        self.bag_size = bag_size
        rows = fluid.layers.data(self.rows_name,
                                 shape=[batch_ids_max, self.dim],
                                 append_batch_size=False,
                                 stop_gradient=False)
        local = fluid.layers.data(self.local_ids_name,
                                  shape=[batch_size, bag_size],
                                  dtype="int64", append_batch_size=False)
        helper = LayerHelper("embedding_bag", name=self.table + "_bag")
        out = helper.create_variable_for_type_inference(rows.dtype)
        helper.append_op(
            type="embedding_bag",
            inputs={"W": [rows], "Ids": [local]},
            outputs={"Out": [out]},
            attrs={"mode": "sum"},
        )
        return out

    def prepare_feed_bags(self, bags):
        """Pull rows for ragged per-sample id bags; returns
        (feed_dict, push_info).  `bags`: sequence of B id sequences (each
        at most bag_size long); shorter bags are -1-padded."""
        if self.max_rows is None or getattr(self, "bag_size", None) is None:
            raise RuntimeError("call lookup_bag() during program build first")
        flat = np.concatenate(
            [np.asarray(b, np.int64).reshape(-1) for b in bags]) \
            if len(bags) else np.zeros((0,), np.int64)
        uniq, inverse = np.unique(flat, return_inverse=True)
        U = len(uniq)
        if U > self.max_rows:
            raise ValueError(
                "batch touches %d unique rows > batch_ids_max=%d"
                % (U, self.max_rows))
        rows = self.client.pull(uniq)
        padded = np.zeros((self.max_rows, self.dim), np.float32)
        padded[:U] = rows
        local = np.full((len(bags), self.bag_size), -1, np.int64)
        off = 0
        for i, b in enumerate(bags):
            k = len(b)
            if k > self.bag_size:
                raise ValueError("bag %d has %d ids > bag_size=%d"
                                 % (i, k, self.bag_size))
            local[i, :k] = inverse[off:off + k]
            off += k
        return ({self.rows_name: padded,
                 self.local_ids_name: local},
                {"uniq": uniq, "n": U, "batch": len(bags)})

    def grad_var(self, program):
        name = self.rows_name + "@GRAD"
        return program.global_block().var(name)

    def prepare_feed(self, ids):
        """Pull touched rows; returns (feed_dict, push_info)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        uniq, inverse = np.unique(ids, return_inverse=True)
        U = len(uniq)
        if self.max_rows is None:
            raise RuntimeError("call lookup() during program build first")
        if U > self.max_rows:
            raise ValueError(
                "batch touches %d unique rows > batch_ids_max=%d"
                % (U, self.max_rows))
        rows = self.client.pull(uniq)
        # zero-pad to the static width so the compiled step keeps one shape
        padded = np.zeros((self.max_rows, self.dim), np.float32)
        padded[:U] = rows
        local = np.zeros((len(ids),), np.int64)
        local[:] = inverse
        # ids feed stays [B]; pad local ids width only if the consumer
        # declared the same static batch — here local ids length == batch
        return ({self.rows_name: padded,
                 self.local_ids_name: local},
                {"uniq": uniq, "n": U, "batch": len(ids)})

    def push_grads(self, info, rows_grad):
        g = np.asarray(rows_grad)[:info["n"]]
        self.client.push(info["uniq"], g)
