"""Elastic re-quorum for the collective all-reduce path.

The PS runtime (distributed/ps.py) evicts dead trainers and re-quorums
sync rounds; this module gives collective (NCCL-style all-reduce) jobs the
same survival story.  A quorum/membership layer runs over the
``PADDLE_COORDINATOR`` control channel (native RpcServer/RpcClient, one
port above each member's data endpoint):

  1. every member heartbeats the quorum coordinator (the lowest-rank live
     member); the coordinator tracks liveness with the PS
     ``HeartBeatMonitor`` and declares a member dead after
     ``FLAGS_elastic_hb_timeout`` seconds of silence;
  2. on death (or a pending rejoin) the coordinator aborts the step gate,
     bumps the quorum *epoch*, and publishes a new membership view: the
     dense rank remap, the survivor count, and a fresh jax.distributed
     coordinator port (``base + epoch * FLAGS_elastic_port_stride``);
  3. every survivor re-runs jax.distributed initialization against the new
     view, re-transpiles its pristine main/startup programs with
     ``GradAllReduce`` for the new ``nranks``/endpoints, passes them
     through ``core/analysis.verify_program`` in **error** mode (including
     DL005: the 1/nranks gradient scale must match the new world), restores
     params from ``io.CheckpointManager.latest_valid()``, and resumes;
  4. a member relaunched by ``launch.py --restart_failed`` rejoins at the
     next epoch: it probes the member list for the live coordinator, posts
     ``__ejoin__``, and adopts the first view that includes it.

Why the old jax world is *parked*, not shut down: jaxlib's coordination
service terminates any process that learns of a peer failure
(``LOG(FATAL)`` in xla/pjrt/distributed/client.h — both the missed-
heartbeat callback and the error-polling thread), and the pybind
``missed_heartbeat_callback`` escape hatch is unusable from Python in this
jaxlib (invoking a Python callback from the C++ thread raises
``std::bad_cast``).  So clients/services are constructed directly with
``shutdown_on_destruction=False`` and heartbeat windows far longer than
any job (the control channel above owns failure detection), and on
re-quorum the dead world's client/service objects are kept referenced
forever: their threads idle on a healthy-looking socket and can never
observe an error.  A process that ever *hosted* a coordination service
must exit via ``finalize()`` (``os._exit``) — C++ destructor order at
interpreter teardown would otherwise close the service under its own
pollers and abort.  Survivor ordering at clean exit: members leave first,
the coordinator leaves last, so no live poller ever sees a dead service.
"""

import hashlib
import json
import logging
import os
import socket
import threading
import time

import numpy as np

from ..core import telemetry as _tm
from ..core import tracing as _tr
from ..native.rpc import RpcClient, RpcServer, EV_SEND
from .ps import HeartBeatMonitor

__all__ = ["ElasticMember", "View", "member_env"]

# control-plane variable names (PS-style __dunder__ namespace)
_HB = "__ehb__"          # <rank>: member heartbeat            [rank, epoch]
_READY = "__eready__"    # <rank>: at the step gate            [epoch, step]
_JOIN = "__ejoin__"      # <rank>: admit me at the next epoch  [rank]
_DONE = "__edone__"      # <rank>: clean completion            [rank]
_ALIVE = "__alive__"     # served by every member's local server
_VIEW = "__eview__"      # latest view; __eview__#<epoch> per epoch
_GATE = "__ego__"        # __ego__#<epoch>:<step> -> [1] go | [0] re-quorum
_STATE = "__estate__"    # peer-restore payload: __estate__#<epoch> meta
                         # (json, uint8) + __estate__#<epoch>#<var> arrays

_GO = 1
_ABORT = 0

# parked-world heartbeat windows: long enough that the coordination
# service never declares anyone dead on its own (the control plane owns
# detection), short enough to be a sane int
_JAX_HB_INTERVAL_S = 3600
_JAX_HB_MAX_MISSING = 10000


def _flag(name):
    from .. import flags

    return flags.flag(name)


def _host_port(endpoint):
    host, port = endpoint.rsplit(":", 1)
    if host in ("localhost", ""):
        host = "127.0.0.1"
    return host, int(port)


def _ctrl_endpoint(member_endpoint):
    host, port = _host_port(member_endpoint)
    return "%s:%d" % (host, port + int(_flag("elastic_ctrl_offset") or 1000))


def _port_free(port):
    s = socket.socket()
    try:
        s.bind(("", port))
        return True
    except OSError:
        return False
    finally:
        s.close()


def _world_fingerprint(*programs):
    """Structural hash of program IR (op types, wiring, attrs).

    Taken at standby build time — right after the full world-level verify —
    and checked again at adoption: an equal fingerprint proves the view is
    byte-for-byte the IR that already passed DL101-104, so adoption can
    skip the (expensive) sibling-rank materialization; any mutation in
    between forces the full blocking re-verify instead."""
    h = hashlib.sha1()
    for prog in programs:
        for blk in prog.blocks:
            for op in blk.ops:
                h.update(json.dumps(op.to_dict(), sort_keys=True,
                                    default=repr).encode())
            h.update(b"|")
    return h.hexdigest()


def member_env():
    """(rank, endpoints, restart_count) from the launcher env."""
    eps = [e for e in os.getenv("PADDLE_TRAINER_ENDPOINTS", "").split(",")
           if e]
    rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
    restarts = int(os.getenv("PADDLE_RESTART_COUNT", "0"))
    return rank, eps, restarts


class View:
    """One quorum epoch's membership: which original ranks are in, who
    coordinates, and where the epoch's jax.distributed service lives.

    ``peer_step``/``peer_src`` carry the peer-to-peer restore offer: the
    newest live post-step state any survivor holds and the lowest rank
    holding it.  (0, -1) means no offer — restore from the filesystem.
    They ride at the TAIL of the wire encoding so old decoders (and
    encodings from old coordinators) stay compatible."""

    __slots__ = ("epoch", "coord_rank", "jax_port", "restore_step", "ranks",
                 "peer_step", "peer_src")

    def __init__(self, epoch, coord_rank, jax_port, restore_step, ranks,
                 peer_step=0, peer_src=-1):
        self.epoch = int(epoch)
        self.coord_rank = int(coord_rank)
        self.jax_port = int(jax_port)
        self.restore_step = int(restore_step)
        self.ranks = tuple(int(r) for r in ranks)
        self.peer_step = int(peer_step)
        self.peer_src = int(peer_src)

    def encode(self):
        return np.asarray([self.epoch, self.coord_rank, self.jax_port,
                           self.restore_step, len(self.ranks)]
                          + list(self.ranks)
                          + [self.peer_step, self.peer_src], np.int64)

    @classmethod
    def decode(cls, arr):
        a = np.asarray(arr).reshape(-1).astype(np.int64)
        n = int(a[4])
        tail = a[5 + n:]
        peer_step, peer_src = ((int(tail[0]), int(tail[1]))
                               if len(tail) >= 2 else (0, -1))
        return cls(a[0], a[1], a[2], a[3], [int(x) for x in a[5:5 + n]],
                   peer_step, peer_src)

    def __repr__(self):
        return ("View(epoch=%d, coord=%d, jax_port=%d, restore=%d, "
                "ranks=%s, peer=%d@%d)"
                % (self.epoch, self.coord_rank, self.jax_port,
                   self.restore_step, list(self.ranks), self.peer_step,
                   self.peer_src))


class _JaxWorld:
    """Direct construction of the jax.distributed client/service so peer
    death cannot abort the process (see module docstring).  Old worlds are
    parked in ``_parked`` — never destroyed."""

    _parked = []
    hosted_service = False

    @classmethod
    def reinit(cls, coord_host, coord_port, num_processes, process_id,
               host_service):
        import jax
        from jax._src import distributed as _dist
        from jax._src.lib import xla_extension as _xe
        from jax.extend import backend as _jexb

        if os.getenv("JAX_PLATFORMS", "").startswith("cpu"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        gs = _dist.global_state
        if gs.client is not None:
            cls._parked.append((gs.client, gs.service,
                                gs.preemption_sync_manager))
            gs.client = None
            gs.service = None
            gs.preemption_sync_manager = None
        _jexb.clear_backends()
        if host_service:
            gs.service = _xe.get_distributed_runtime_service(
                "[::]:%d" % coord_port, num_processes,
                heartbeat_interval=_JAX_HB_INTERVAL_S,
                max_missing_heartbeats=_JAX_HB_MAX_MISSING)
            cls.hosted_service = True
        gs.client = _xe.get_distributed_runtime_client(
            "%s:%d" % (coord_host, coord_port), process_id,
            init_timeout=120, heartbeat_interval=_JAX_HB_INTERVAL_S,
            max_missing_heartbeats=_JAX_HB_MAX_MISSING,
            shutdown_on_destruction=False)
        gs.client.connect()
        gs.process_id = process_id
        gs.num_processes = num_processes
        gs.coordinator_address = "%s:%d" % (coord_host, coord_port)


class _Coordinator(threading.Thread):
    """Quorum state machine; runs inside the coordinator member's process
    on that member's control RpcServer."""

    def __init__(self, member, epoch, ranks, join_window_s=0.0):
        super().__init__(name="elastic-coord", daemon=True)
        self.m = member
        self.srv = member._server
        self.epoch = int(epoch)
        self.live = set(int(r) for r in ranks)
        self.joins = set()
        self.done = set()
        self.ready = {}          # (epoch, step) -> set(ranks)
        self.released = []       # published gate keys (pruned)
        self.aborted = set()     # epochs whose gates answer [0]
        self.state = {}          # rank -> (state_step, has_state) from HB/READY
        self._stop = False
        self._detect_t0 = None
        # a freshly failed-over coordinator waits for survivors to rejoin
        # before forming its first view
        self._join_deadline = (time.time() + join_window_s
                               if join_window_s else None)
        timeout = float(_flag("elastic_hb_timeout") or 5.0)
        self.mon = HeartBeatMonitor(0, timeout_s=timeout, name="elastic",
                                    worker_ids=sorted(self.live))
        self.all_done = threading.Event()
        self._publish_view(View(self.epoch, member.rank,
                                self._pick_port(self.epoch),
                                self._restore_step(), sorted(self.live)))

    # -- helpers ------------------------------------------------------------

    def _restore_step(self):
        ckpt = self.m.ckpt
        if ckpt is None:
            return 0
        try:
            found = ckpt.latest_valid()
        except Exception:
            found = None
        return found[0] if found else 0

    def _peer_fields(self, fs_step):
        """(peer_step, peer_src) offer for the next view: the newest live
        post-step state among surviving members, preferred over the
        filesystem whenever it is at least as fresh as latest_valid() —
        survivors that stepped past the last checkpoint would DIVERGE the
        world if a rejoiner read the stale fs copy.  (0, -1) when p2p
        restore is off (the coordinator's flag decides for the whole world,
        so every member takes the same path) or nobody holds usable state."""
        if not _flag("checkpoint_p2p_restore"):
            return 0, -1
        cands = {r: s for r, (s, h) in self.state.items()
                 if r in self.live and h and s > 0}
        if not cands:
            return 0, -1
        peer_step = max(cands.values())
        if peer_step < int(fs_step):
            return 0, -1
        src = min(r for r, s in cands.items() if s == peer_step)
        return int(peer_step), int(src)

    def _pick_port(self, epoch):
        base = _host_port(self.m.members[self.m.rank])[1]
        stride = int(_flag("elastic_port_stride") or 29)
        port = base + stride * epoch
        for _ in range(32):
            if epoch == 0 or _port_free(port):
                return port
            port += stride
        return port

    def _publish_view(self, view):
        self.view = view
        enc = view.encode()
        self.srv.set_var("%s#%d" % (_VIEW, view.epoch), enc)
        self.srv.set_var(_VIEW, enc)
        self.srv.serve(True)
        _tm.set_gauge("elastic_epoch", view.epoch)
        _tm.set_gauge("elastic_world_size", len(view.ranks))

    def _release(self, epoch, step, value):
        key = "%s#%d:%d" % (_GATE, epoch, step)
        self.srv.set_var(key, np.asarray([value], np.int64))
        if key not in self.released:
            self.released.append(key)
        while len(self.released) > 16:
            self.srv.del_var(self.released.pop(0))

    # -- event handling -----------------------------------------------------

    def run(self):
        while not self._stop:
            t, name, arr = self.srv.poll()
            if t == 0:
                return
            if t == EV_SEND:
                self._on_event(name, arr)
            self._tick()

    def _on_event(self, name, arr):
        if name.startswith(_HB):
            a = np.asarray(arr).reshape(-1)
            r = int(a[0])
            if r in self.live:
                self.mon.update(r)
            if len(a) >= 4:  # extended HB carries (state_step, has_state)
                self.state[r] = (int(a[2]), int(a[3]))
        elif name.startswith(_READY):
            r = int(name[len(_READY):])
            epoch, step = int(arr[0]), int(arr[1])
            if r in self.live:
                self.mon.update(r)
            # a member at the gate holds live state for `step` done steps
            self.state[r] = (step, 1)
            if epoch in self.aborted or epoch < self.epoch:
                self._release(epoch, step, _ABORT)
                return
            got = self.ready.setdefault((epoch, step), set())
            got.add(r)
            if got >= self.live:
                self._release(epoch, step, _GO)
                self.ready.pop((epoch, step), None)
        elif name.startswith(_JOIN):
            r = int(arr[0])
            if r not in self.live:
                self.joins.add(r)
        elif name.startswith(_DONE):
            r = int(arr[0])
            self.done.add(r)
            self.mon.remove(r)
            self.live.discard(r)
            # release any gate the remaining members are parked on
            for (epoch, step), got in list(self.ready.items()):
                if epoch == self.epoch and got >= self.live:
                    self._release(epoch, step, _GO)
                    self.ready.pop((epoch, step), None)
            if not self.live - {self.m.rank}:
                self.all_done.set()

    def _tick(self):
        # the coordinator's own process is trivially alive while this code
        # runs — never let a stalled local HB thread (GIL contention from a
        # standby compile, a wedged shared RPC client) self-evict the
        # quorum's anchor; coordinator death is the members' failover path
        if self.m.rank in self.live:
            self.mon.update(self.m.rank)
        dead = [r for r in self.mon.check() if r in self.live]
        joining = self.joins - self.live
        if dead and self._detect_t0 is None:
            self._detect_t0 = time.perf_counter()
        if self._join_deadline is not None:
            if time.time() < self._join_deadline:
                return
            self._join_deadline = None
            self._requorum(dead)
            return
        if dead or joining:
            self._requorum(dead)

    def _requorum(self, dead):
        t0 = self._detect_t0 or time.perf_counter()
        self._detect_t0 = None
        old_epoch = self.epoch
        evicted = sorted(set(dead) & self.live)
        joined = sorted(self.joins - self.live)
        self.live = (self.live - set(evicted)) | set(joined)
        self.joins.clear()
        self.epoch += 1
        self.aborted.add(old_epoch)
        self.state = {r: v for r, v in self.state.items() if r in self.live}
        # wake every member parked at an old-epoch gate
        for (epoch, step), _ in list(self.ready.items()):
            if epoch <= old_epoch:
                self._release(epoch, step, _ABORT)
                self.ready.pop((epoch, step), None)
        fs_step = self._restore_step()
        peer_step, peer_src = self._peer_fields(fs_step)
        view = View(self.epoch, self.m.rank, self._pick_port(self.epoch),
                    fs_step, sorted(self.live), peer_step, peer_src)
        # grace: a joiner needs time to init jax + transpile + restore
        timeout = float(_flag("elastic_hb_timeout") or 5.0)
        self.mon = HeartBeatMonitor(0, timeout_s=timeout, name="elastic",
                                    worker_ids=sorted(self.live))
        self._publish_view(view)
        ms = (time.perf_counter() - t0) * 1e3
        if evicted:
            _tm.inc("elastic_evictions_total", len(evicted))
        if joined:
            _tm.inc("elastic_rejoins_total", len(joined))
        _tm.observe("elastic_requorum_ms", ms, role="coordinator")
        _tm.event("elastic_epoch", epoch=self.epoch,
                  world=len(view.ranks), evicted=evicted, joined=joined,
                  restore_step=view.restore_step, ms=round(ms, 3),
                  peer_step=view.peer_step, peer_src=view.peer_src)
        logging.warning(
            "[elastic] epoch %d: world=%s evicted=%s joined=%s "
            "jax_port=%d restore_step=%d peer=%d@%d", self.epoch,
            sorted(self.live), evicted, joined, view.jax_port,
            view.restore_step, view.peer_step, view.peer_src)

    def stop(self):
        self._stop = True


class ElasticMember:
    """Member-side elastic runtime for a collective all-reduce job.

    Usage (see tests/dist_elastic_payload.py)::

        member = ElasticMember(main, startup, executor=exe, ckpt=mgr,
                               feed_names=["x", "y"],
                               fetch_names=[loss.name])
        member.start()                    # quorum + jax init + transpile +
        step = member.restore_step        #   verify + restore
        while step < total_steps:
            if not member.gate(step):     # False -> re-quorumed
                step = member.restore_step
                continue
            out = exe.run(member.main_program, feed=shard(step, member),
                          fetch_list=[member.fetch_names[0]])
            step += 1
            ...checkpoint via member.maybe_save(step)...
        member.finalize()

    ``main``/``startup`` are the PRISTINE (un-transpiled) programs; every
    epoch clones them and applies ``GradAllReduce`` for that epoch's world,
    then verifies the rewrite in error mode (DL001-005) before the executor
    may recompile it."""

    def __init__(self, main_program, startup_program, executor=None,
                 ckpt=None, feed_names=(), fetch_names=(), members=None,
                 rank=None, nrings=1, scope=None, feed_specs=None):
        env_rank, env_eps, env_restarts = member_env()
        self.rank = env_rank if rank is None else int(rank)
        self.members = list(members) if members is not None else env_eps
        if not self.members:
            raise ValueError("no member endpoints: pass members= or set "
                             "PADDLE_TRAINER_ENDPOINTS")
        self.restart_count = env_restarts
        self.base_main = main_program
        self.base_startup = startup_program
        self.executor = executor
        self.ckpt = ckpt
        self.scope = scope
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.nrings = int(nrings)
        # feed signature for pre-compilation: {name: (shape, dtype)} or a
        # callable world_size -> that dict (per-member batch shards shrink
        # when the world does).  Enables the standby pre-compile and the
        # post-adopt warmup; without it only transpile+verify are standby.
        self.feed_specs = feed_specs
        self.view = None
        self.main_program = None
        self.startup_program = None
        self.restore_step = 0
        self._server = None
        self._coord = None
        self._ctrl = None        # send-side client to the coordinator
        self._gate_c = None      # blocking-get client to the coordinator
        self._hb_thread = None
        self._stop_hb = threading.Event()
        self._finalized = False
        # standby views: frozenset(ranks) -> pre-transpiled/verified (and,
        # with feed_specs, tier-B pre-compiled) programs for a world this
        # member might shrink into (see _spawn_standby)
        self._standby = {}
        self._standby_lock = threading.Lock()
        self._standby_thread = None
        # last adoption's phase breakdown (ms) + whether a standby view
        # served it — payloads/tests read these after gate() returns False
        self.last_adopt_phases = {}
        self.last_adopt_standby = False
        # where the last adoption's state came from: "peer" | "fs" | None
        self.last_restore_source = None
        # live-state bookkeeping for peer-to-peer restore: how many steps
        # this member has COMPLETED (updated at the gate and after adopt)
        # and whether the scope holds adopted state at all
        self._state_step = 0
        self._has_state = False
        self._published_state = []  # __estate__ keys served for rejoiners

    # -- properties ----------------------------------------------------------

    @property
    def epoch(self):
        return self.view.epoch if self.view else -1

    @property
    def world(self):
        return len(self.view.ranks) if self.view else 0

    @property
    def pid(self):
        """Dense process id in the current view (jax process_id)."""
        return self.view.ranks.index(self.rank)

    def is_coordinator(self):
        return self.view is not None and self.view.coord_rank == self.rank

    def fetch_var(self, name):
        return self.main_program.global_block().var(name)

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """Form or join the quorum, then adopt the current view (jax init,
        transpile, verify, startup + checkpoint restore)."""
        host, port = _host_port(self.members[self.rank])
        ctrl_port = port + int(_flag("elastic_ctrl_offset") or 1000)
        self._server = RpcServer(port=ctrl_port)
        self._server.set_var(_ALIVE, np.asarray([self.rank, 0, 0], np.int64))
        self._server.serve(True)

        fresh_seed = self.restart_count == 0
        if self.rank == min(range(len(self.members))) and fresh_seed:
            self._become_coordinator(epoch=0,
                                     ranks=range(len(self.members)))
            coord_rank = self.rank
        else:
            coord_rank = self._find_coordinator()
        self._connect_ctrl(coord_rank)
        self._start_heartbeat()

        view = self._wait_view_with_me()
        self._adopt(view)
        return self

    def _become_coordinator(self, epoch, ranks, join_window_s=0.0):
        self._coord = _Coordinator(self, epoch, ranks,
                                   join_window_s=join_window_s)
        self._server.set_var(
            _ALIVE, np.asarray([self.rank, epoch, 1], np.int64))
        self._coord.start()

    def _find_coordinator(self, window_s=90.0):
        """Probe the member list (rank order) for the live coordinator."""
        deadline = time.time() + window_s
        while time.time() < deadline:
            for r, ep in enumerate(self.members):
                if r == self.rank:
                    continue
                got = self._probe_alive(ep)
                if got is not None and got[2] == 1:
                    return r
            time.sleep(0.3)
        raise ConnectionError(
            "[elastic] rank %d: no live coordinator among %s within %.0fs"
            % (self.rank, self.members, window_s))

    def _probe_alive(self, member_endpoint):
        from ..native import rpc as _rpc

        got = _rpc.probe(_ctrl_endpoint(member_endpoint), key=_ALIVE)
        return None if got is None else [int(x) for x in got]

    def _connect_ctrl(self, coord_rank):
        for c in (self._ctrl, self._gate_c):
            if c is not None:
                try:
                    c.close()
                except Exception:
                    pass
        ep = _ctrl_endpoint(self.members[coord_rank])
        self._coord_rank_hint = coord_rank
        self._ctrl = RpcClient(ep, connect_timeout=60.0, rpc_deadline=10.0,
                               retry_times=3)
        self._gate_c = RpcClient(ep, connect_timeout=60.0, rpc_deadline=60.0,
                                 retry_times=1)

    def _start_heartbeat(self):
        if self._hb_thread is not None:
            return

        def loop():
            interval = float(_flag("elastic_hb_interval") or 0.5)
            name = _HB + str(self.rank)
            while not self._stop_hb.wait(interval):
                try:
                    # extended HB: (state_step, has_state) lets the
                    # coordinator compute the next view's peer-restore offer
                    self._ctrl.send_var(name, np.asarray(
                        [self.rank, self.epoch, int(self._state_step),
                         1 if self._has_state else 0], np.int64))
                except Exception:
                    pass  # gate() owns failure handling

        self._hb_thread = threading.Thread(target=loop, name="elastic-hb",
                                           daemon=True)
        self._hb_thread.start()

    def _wait_view_with_me(self, window_s=180.0):
        """Fetch the current view; if this member was evicted (or is a
        rejoiner), post __ejoin__ and wait for an epoch that includes it."""
        deadline = time.time() + window_s
        asked = False
        while time.time() < deadline:
            view = View.decode(self._gate_c.get_var(_VIEW))
            if self.rank in view.ranks:
                return view
            if not asked:
                self._ctrl.send_var(_JOIN + str(self.rank),
                                    np.asarray([self.rank], np.int64))
                _tm.event("elastic_join_request", rank=self.rank,
                          epoch=view.epoch)
                asked = True
            time.sleep(0.3)
        raise TimeoutError("[elastic] rank %d not admitted within %.0fs"
                           % (self.rank, window_s))

    # -- epoch adoption ------------------------------------------------------

    def _numpyify_scope(self):
        """Detach scope tensors from the dying backend: every value becomes
        a host numpy array before clear_backends invalidates jax.Arrays."""
        scope = self.scope
        if scope is None and self.executor is not None:
            from ..core.executor import global_scope

            scope = global_scope()
        if scope is None:
            return
        s = scope
        while s is not None:
            for name in s.local_var_names():
                var = s.find_var(name)
                t = var.get_tensor() if var else None
                if t is not None and t._is_initialized():
                    try:
                        # np.asarray of a CPU jax.Array can alias the XLA
                        # buffer — a real copy is required or the "detached"
                        # value dangles once clear_backends frees the buffer
                        # (the peer-restore path reads these post-reset)
                        t.set(np.array(t.get(), copy=True))
                    except Exception:
                        pass
            s = getattr(s, "parent", None)

    def _adopt(self, view):
        """Make `view` this process's world: jax re-init, then either
        consume a fresh standby view (transpile+verify already done and the
        executable pre-compiled into the tier-B disk cache — re-quorum
        collapses to cache-restore + checkpoint-restore) or re-transpile +
        re-verify from the pristine programs; finally startup + warmup +
        restore.  Each phase lands in the elastic_requorum_phase_ms
        histogram so the breakdown is auditable."""
        t0 = time.perf_counter()
        old_epoch = self.epoch
        self.view = view
        self._server.set_var(_ALIVE, np.asarray(
            [self.rank, view.epoch, 1 if self._coord else 0], np.int64))
        pid = view.ranks.index(self.rank)
        world = len(view.ranks)
        coord_host = _host_port(self.members[view.coord_rank])[0]

        self._numpyify_scope()
        # survivors hold live post-step state right here (numpy, detached
        # from the dying backend) — capture the refs BEFORE run(startup)
        # re-initializes the scope; scope.var().set replaces array objects,
        # so these refs stay intact.  If this member is the view's peer
        # source, serve the state on the ctrl server NOW so a rejoining
        # member can fetch it while we transpile/compile.
        live_state = self._capture_live_state(view) if old_epoch >= 0 else None
        if live_state is not None and view.peer_src == self.rank:
            self._publish_live_state(view, live_state)
        # everything below mutates the scope (run(startup) re-inits, warmup
        # may touch buffers) — if this adoption dies mid-way and another
        # re-quorum follows, a capture against the half-rebuilt scope would
        # serve init values as if they were step-N state.  Invalidate until
        # the adoption completes; live_state above is already detached.
        self._has_state = False
        if self.executor is not None:
            self.executor.reset_device_state()
        _JaxWorld.reinit(coord_host, view.jax_port, world, pid,
                         host_service=self.rank == view.coord_rank)
        phases = {"init": (time.perf_counter() - t0) * 1e3}

        standby = self._take_standby(view) if old_epoch >= 0 else None
        if standby is not None:
            # pre-transpiled + pre-verified in the background after the
            # last adoption: the transpile phase is already paid, and the
            # verify is too IF the IR fingerprint still matches what was
            # hashed right after the standby-build verify.  In error mode
            # a view tampered or staled between build and adoption fails
            # that check and goes through the full world-level re-verify,
            # which raises — it can never be adopted with a latent
            # deadlock.
            main, startup = standby["main"], standby["startup"]
            phases["transpile"] = 0.0
            tampered = (_flag("static_check") == "error"
                        and _world_fingerprint(main, startup)
                        != standby.get("verified_fp"))
            phases["verify"] = 0.0
            if tampered:
                tv = time.perf_counter()
                self._verify(main, startup, world, pid=pid)
                phases["verify"] = (time.perf_counter() - tv) * 1e3
        else:
            # re-transpile pristine programs for the new world + verify the
            # rewrite loudly BEFORE any recompile (DL001-006, error mode)
            endpoints = [self.members[r] for r in view.ranks]
            main = self.base_main.clone()
            startup = self.base_startup.clone()
            # FLAGS_collective_mode-aware: a zero1 job re-shards the
            # optimizer state for the new world here (the re-transpiled
            # shard assignment covers `world` ranks; shard-local slots
            # rematerialize from the full arrays the checkpoint restore
            # puts back into the scope)
            from ..transpiler.collective import select_grad_transpiler

            t1 = time.perf_counter()
            t = select_grad_transpiler(self.nrings)
            t.transpile(startup_program=startup, main_program=main,
                        rank=pid, endpoints=endpoints,
                        current_endpoint=self.members[self.rank],
                        wait_port=False)
            t2 = time.perf_counter()
            self._verify(main, startup, world, pid=pid)
            phases["transpile"] = (t2 - t1) * 1e3
            phases["verify"] = (time.perf_counter() - t2) * 1e3
        # the pool only held subsets of the OLD view; rebuild below
        with self._standby_lock:
            self._standby.clear()
        self.main_program = main
        self.startup_program = startup

        self.restore_step = 0
        self.last_restore_source = None
        phases["compile"] = phases["restore"] = 0.0
        if self.executor is not None:
            tc = time.perf_counter()
            self.executor.run(startup)
            if self.feed_specs is not None and self.fetch_names:
                # pre-compile the training step now so the compile cost is
                # attributed to this phase, not smeared into the first
                # post-restore step; with a pre-compiled standby this is a
                # tier-B disk restore, not an XLA compile
                specs = (self.feed_specs(world) if callable(self.feed_specs)
                         else self.feed_specs)
                try:
                    got = self.executor.warmup(
                        main, feed_specs=specs,
                        fetch_list=list(self.fetch_names))
                    _tm.event("elastic_warmup", rank=self.rank,
                              epoch=view.epoch, source=got["source"],
                              ms=round(got["compile_ms"], 3))
                except Exception as e:
                    logging.warning("[elastic] post-adopt warmup failed: "
                                    "%s", e)
            phases["compile"] = (time.perf_counter() - tc) * 1e3
            tr = time.perf_counter()
            src = None
            if view.peer_step > 0 and live_state is not None \
                    and self._state_step == view.peer_step:
                # survivor: its own pre-requorum state IS the adopted state
                self._set_state(main, live_state)
                self.restore_step = int(view.peer_step)
                src = "peer"
            elif view.peer_step > 0 and 0 <= view.peer_src < len(self.members) \
                    and view.peer_src != self.rank:
                # rejoiner (or a lagging survivor): fetch from the peer
                # source over the native-RPC fabric instead of the fs
                try:
                    self._peer_fetch(view, main)
                    self.restore_step = int(view.peer_step)
                    src = "peer"
                except Exception as e:
                    logging.warning(
                        "[elastic] rank %d: peer restore from rank %d "
                        "failed (%s) — falling back to filesystem",
                        self.rank, view.peer_src, e)
            if src is None and self.ckpt is not None:
                try:
                    self.ckpt.wait()  # drain an in-flight async write
                except Exception as e:
                    logging.warning("[elastic] pending checkpoint write "
                                    "failed: %s", e)
                step, _extra = self.ckpt.restore(self.executor, main)
                self.restore_step = int(step)
                src = "fs"
            if src is not None:
                _tm.inc("checkpoint_restore_source_total", source=src)
                _tm.event("elastic_restore", rank=self.rank,
                          epoch=view.epoch, step=self.restore_step,
                          source=src)
            self.last_restore_source = src
            phases["restore"] = (time.perf_counter() - tr) * 1e3
        ms = (time.perf_counter() - t0) * 1e3
        _tm.observe("elastic_requorum_ms", ms, role="member")
        for ph in ("transpile", "verify", "compile", "restore"):
            _tm.observe("elastic_requorum_phase_ms", phases[ph], phase=ph)
        if _tr.enabled():
            # the phases were measured as perf_counter deltas; lay them
            # out retroactively as one span tree per adoption epoch, the
            # phase children sequential from the adoption's wall start
            wall0 = time.time() - ms / 1e3
            root = _tr.record_span(
                "elastic.requorum", wall0, ms, epoch=view.epoch,
                world=world, rank=self.rank, standby=standby is not None)
            cursor = wall0
            for ph in ("init", "transpile", "verify", "compile",
                       "restore"):
                attrs, links = {}, None
                if ph == "restore" and self.last_restore_source:
                    # flow from the checkpoint span tree into the phase:
                    # the fs path links the checkpoint.restore span that
                    # served it (trace_view renders the arrow)
                    attrs["source"] = self.last_restore_source
                    if (self.last_restore_source == "fs"
                            and self.ckpt is not None):
                        links = [getattr(self.ckpt, "last_restore_span",
                                         None)]
                _tr.record_span("elastic." + ph, cursor, phases[ph],
                                parent=root, links=links, **attrs)
                cursor += phases[ph] / 1e3
        _tm.set_gauge("elastic_epoch", view.epoch)
        if old_epoch >= 0:
            _tm.event("elastic_adopt", rank=self.rank, epoch=view.epoch,
                      world=world, ms=round(ms, 3),
                      standby=standby is not None,
                      phases={k: round(v, 3) for k, v in phases.items()})
        self.last_adopt_phases = dict(phases)
        self.last_adopt_standby = standby is not None
        # adopted state covers restore_step completed steps; gate() keeps
        # _state_step current from here on
        self._state_step = int(self.restore_step)
        self._has_state = self.executor is not None
        logging.info(
            "[elastic] rank %d adopted %r (pid %d/%d) in %.0fms "
            "(standby=%s transpile=%.0f verify=%.0f compile=%.0f "
            "restore=%.0f)", self.rank, view, pid, world, ms,
            standby is not None, phases["transpile"], phases["verify"],
            phases["compile"], phases["restore"])
        self._spawn_standby()

    # -- peer-to-peer state movement ----------------------------------------

    def _live_scope(self):
        if self.scope is not None:
            return self.scope
        from ..core.executor import global_scope

        return global_scope()

    def _persistable_names(self, program):
        return {v.name for v in program.list_vars()
                if v.persistable and not v.is_data}

    def _capture_live_state(self, view):
        """{name: host ndarray} of the persistable scope state, or None when
        this member's progress doesn't match the view's peer offer (it
        crashed behind, or the offer is empty).  Called right after
        _numpyify_scope, so every ref is already a plain numpy array."""
        if (view.peer_step <= 0 or self.executor is None
                or self.main_program is None
                or not self._has_state
                or self._state_step != view.peer_step):
            return None
        scope = self._live_scope()
        out = {}
        for name in self._persistable_names(self.main_program):
            var = scope.find_var(name)
            t = var.get_tensor() if var else None
            if t is None:
                continue
            # ALL-OR-NOTHING: a var whose backend buffer was donated away
            # (deleted jax.Array) or never materialized would silently keep
            # its startup-init value after _set_state — a partial capture
            # restored as if complete diverges the rank bitwise.  Fail the
            # whole capture instead; the adoption falls back to peer-fetch
            # or the filesystem checkpoint, both of which are complete.
            try:
                if not t._is_initialized():
                    raise RuntimeError("uninitialized")
                out[name] = np.array(t.get(), copy=True)
            except Exception as e:
                logging.warning(
                    "[elastic] rank %d: live-state capture failed on %r "
                    "(%s); falling back to peer/fs restore", self.rank,
                    name, e)
                return None
        return out or None

    def _set_state(self, program, state):
        scope = self._live_scope()
        names = self._persistable_names(program)
        for name, arr in state.items():
            if name in names:
                scope.var(name).set(arr)

    def _publish_live_state(self, view, state):
        """Serve this member's live state on its ctrl server for rejoiners:
        one meta var (json describing step/names/shapes/dtypes — the wire
        flattens arrays) plus one var per tensor.  Previous epochs' payload
        is dropped first so state from at most one epoch is ever held."""
        for key in self._published_state:
            try:
                self._server.del_var(key)
            except Exception:
                pass
        self._published_state = []
        meta = {"step": int(view.peer_step),
                "names": sorted(state),
                "shapes": {n: list(np.shape(a)) for n, a in state.items()},
                "dtypes": {n: str(np.asarray(a).dtype)
                           for n, a in state.items()}}
        mkey = "%s#%d" % (_STATE, view.epoch)
        self._server.set_var(mkey, np.frombuffer(
            json.dumps(meta).encode(), np.uint8).copy())
        self._published_state.append(mkey)
        for name, arr in state.items():
            key = "%s#%d#%s" % (_STATE, view.epoch, name)
            self._server.set_var(key, np.asarray(arr))
            self._published_state.append(key)
        _tm.event("elastic_state_published", rank=self.rank,
                  epoch=view.epoch, step=view.peer_step, vars=len(state))

    def _peer_fetch(self, view, program):
        """Pull the peer source's live state over the native-RPC fabric and
        set it into the scope (blocking gets: the publisher serves the
        payload before its own slow adoption phases)."""
        ep = _ctrl_endpoint(self.members[view.peer_src])
        c = RpcClient(ep, connect_timeout=60.0, rpc_deadline=60.0,
                      retry_times=1)
        try:
            raw = np.asarray(c.get_var("%s#%d" % (_STATE, view.epoch)))
            meta = json.loads(raw.astype(np.uint8).tobytes().decode())
            if int(meta["step"]) != int(view.peer_step):
                raise RuntimeError("peer state step %s != offered %d"
                                   % (meta["step"], view.peer_step))
            scope = self._live_scope()
            names = self._persistable_names(program)
            got = 0
            for name in meta["names"]:
                if name not in names:
                    continue
                arr = np.asarray(c.get_var(
                    "%s#%d#%s" % (_STATE, view.epoch, name)))
                arr = arr.reshape(meta["shapes"][name]).astype(
                    meta["dtypes"][name], copy=False)
                scope.var(name).set(arr)
                got += 1
            _tm.event("elastic_state_fetched", rank=self.rank,
                      epoch=view.epoch, src=view.peer_src, vars=got)
        finally:
            try:
                c.close()
            except Exception:
                pass

    def _verify(self, main, startup, world, pid=None):
        from ..core import analysis

        for prog, label in ((main, "main"), (startup, "startup")):
            rep = analysis.verify_program(
                prog, feed_names=self.feed_names if prog is main else (),
                fetch_names=self.fetch_names if prog is main else (),
                label="elastic epoch %d %s" % (self.view.epoch, label),
                expected_nranks=world)
            if rep.errors:
                raise analysis.ProgramVerificationError(rep)
        # whole-world pass: materialize the sibling ranks from the
        # pristine base programs and match THIS view's collective schedule
        # against them in lockstep (DL101-104 + the MEM estimator) — a
        # standby or re-transpiled view carrying a latent cross-rank
        # deadlock can never be adopted
        if pid is not None and int(world) > 1:
            from ..core import world_analysis

            rep = world_analysis.verify_world(
                self.base_main, self.base_startup, world,
                nrings=self.nrings,
                actual={int(pid): (main, startup)},
                feed_names=list(self.feed_names or ()) or None,
                fetch_names=list(self.fetch_names or ()),
                label="elastic epoch %d world of %d"
                      % (self.view.epoch, int(world)))
            if rep.errors:
                raise analysis.ProgramVerificationError(rep)

    # -- standby views -------------------------------------------------------
    #
    # After every adoption a background thread prepares the worlds this
    # member is most likely to shrink into — every single-member loss
    # (world N-1) and the loss of the two highest-ranked peers (world N-2)
    # — by cloning + re-transpiling + verifying the pristine programs NOW,
    # and (when feed_specs is known) pre-compiling the step executable over
    # a device-prefix mesh into the tier-B disk cache.  A later re-quorum
    # that lands on a prepared rank set skips transpile + verify outright
    # and restores the executable from disk instead of recompiling.

    def _standby_flags_sig(self):
        from .. import flags as _flags

        return tuple(sorted(_flags.get_flags(
            ["FLAGS_collective_mode", "FLAGS_allreduce_dtype",
             "FLAGS_allreduce_quant_bucket"]).items()))

    def _standby_candidates(self):
        """Rank subsets (each containing this member) for worlds N-1/N-2,
        by FLAGS_elastic_standby depth."""
        if self.view is None:
            return []
        depth = int(_flag("elastic_standby") or 0)
        ranks = set(self.view.ranks)
        others = sorted(r for r in ranks if r != self.rank)
        cands = []
        if depth >= 1 and len(ranks) >= 2:
            for r in others:
                cands.append(tuple(sorted(ranks - {r})))
        if depth >= 2 and len(ranks) >= 3:
            cands.append(tuple(sorted(ranks - set(others[-2:]))))
        return cands

    def _build_standby(self, ranks):
        """Transpile + verify (error mode) one candidate world; with
        feed_specs, also pre-compile its step into the tier-B cache over
        jax.devices()[:world] (device ids are not part of the tier-B key,
        so the artifact is loadable by the re-initialized backend)."""
        ranks = tuple(sorted(int(r) for r in ranks))
        if self.rank not in ranks:
            raise ValueError("standby ranks %s exclude self (%d)"
                             % (list(ranks), self.rank))
        pid = ranks.index(self.rank)
        world = len(ranks)
        endpoints = [self.members[r] for r in ranks]
        from ..transpiler.collective import select_grad_transpiler

        main = self.base_main.clone()
        startup = self.base_startup.clone()
        t = select_grad_transpiler(self.nrings)
        t.transpile(startup_program=startup, main_program=main, rank=pid,
                    endpoints=endpoints,
                    current_endpoint=self.members[self.rank],
                    wait_port=False)
        self._verify(main, startup, world, pid=pid)
        rec = {"ranks": ranks, "main": main, "startup": startup,
               "flags_sig": self._standby_flags_sig(),
               "base_versions": (self.base_main.version,
                                 self.base_startup.version),
               "compiled": False}
        if self.executor is not None and self.feed_specs is not None \
                and self.fetch_names:
            import jax

            specs = (self.feed_specs(world) if callable(self.feed_specs)
                     else self.feed_specs)
            # jax.devices() is the GLOBAL list: its first `world` entries
            # need not include any device this process can address, and
            # materializing params onto a mesh with zero addressable
            # shards raises a bare StopIteration from deep inside jax.
            # Put our own device at this rank's standby position and fill
            # the rest from the remaining global pool — the tier-B key
            # carries no device ids, so the artifact stays loadable by the
            # re-initialized post-requorum backend either way.
            local = jax.local_devices()[0]
            pool = [d for d in jax.devices() if d != local]
            devs = [local if i == pid else pool.pop(0)
                    for i in range(world)]
            try:
                # the startup program bakes the world size into its
                # c_comm_init nranks attr, so the shrunk world's startup is
                # a distinct executable — pre-compile it too or the
                # re-quorum's executor.run(startup) pays a fresh XLA compile
                self.executor.warmup(startup, feed_specs={}, fetch_list=[],
                                     devices=devs)
            except Exception as e:
                logging.warning("[elastic] standby startup pre-compile for "
                                "world %s failed: %r", list(ranks), e)
            for attempt in (0, 1):
                try:
                    got = self.executor.warmup(
                        main, feed_specs=specs,
                        fetch_list=list(self.fetch_names), devices=devs)
                    rec["compiled"] = got["source"] in ("compiled", "disk")
                    break
                except Exception as e:
                    # racing the training loop: a donated param can vanish
                    # mid-gather — retry once, then settle for
                    # transpile+verify-only standby
                    if attempt:
                        logging.warning("[elastic] standby pre-compile for "
                                        "world %s failed: %r", list(ranks), e)
                        _tm.inc("elastic_standby_errors_total")
        # hash AFTER the warmup pre-compile: the executor may fuse
        # optimizer ops in place there, and the adoption-time check must
        # see the IR exactly as it will be handed over
        rec["verified_fp"] = _world_fingerprint(main, startup)
        with self._standby_lock:
            self._standby[frozenset(ranks)] = rec
        _tm.inc("elastic_standby_built_total")
        _tm.event("elastic_standby", rank=self.rank, world=world,
                  ranks=list(ranks), compiled=rec["compiled"])
        return rec

    def _take_standby(self, view):
        """Fresh standby programs for exactly `view.ranks`, or None.
        Freshness: built from the current base program versions under the
        current transpile-affecting flags."""
        with self._standby_lock:
            rec = self._standby.get(frozenset(view.ranks))
        if rec is None:
            _tm.inc("elastic_standby_miss_total")
            return None
        if (rec["flags_sig"] != self._standby_flags_sig()
                or rec["base_versions"] != (self.base_main.version,
                                            self.base_startup.version)):
            _tm.inc("elastic_standby_stale_total")
            return None
        _tm.inc("elastic_standby_hits_total")
        return rec

    def prepare_standby_views(self, ranks_list=None):
        """Synchronously build standby views (tests / explicit prewarm).
        Defaults to the automatic N-1/N-2 candidate set."""
        built = []
        for ranks in (ranks_list if ranks_list is not None
                      else self._standby_candidates()):
            built.append(self._build_standby(ranks))
        return built

    def _spawn_standby(self):
        if int(_flag("elastic_standby") or 0) <= 0:
            return
        cands = self._standby_candidates()
        if not cands:
            return

        def work():
            for ranks in cands:
                if self._stop_hb.is_set():
                    return
                try:
                    self._build_standby(ranks)
                except Exception as e:
                    logging.warning("[elastic] standby build %s failed: %s",
                                    list(ranks), e)
                    _tm.inc("elastic_standby_errors_total")

        th = threading.Thread(target=work, name="elastic-standby",
                              daemon=True)
        self._standby_thread = th
        th.start()

    def wait_standby(self, timeout=60.0):
        """Block until the background standby builder finishes; -> dict of
        prepared rank tuples -> pre-compiled?  (tests use this to make the
        standby-hit deterministic)."""
        th = self._standby_thread
        if th is not None:
            th.join(timeout)
        with self._standby_lock:
            return {tuple(sorted(k)): v["compiled"]
                    for k, v in self._standby.items()}

    # -- step gate -----------------------------------------------------------

    def gate(self, step):
        """Barrier before `step`.  True -> proceed; False -> the quorum
        re-formed: programs/restore_step were replaced, restart the loop
        from self.restore_step."""
        epoch = self.epoch
        # at the gate for `step`, exactly `step` steps are complete — this
        # is the state a re-quorum's peer-restore offer would broadcast
        self._state_step = int(step)
        try:
            self._ctrl.send_var(_READY + str(self.rank),
                                np.asarray([epoch, step], np.int64))
            verdict = int(self._gate_c.get_var(
                "%s#%d:%d" % (_GATE, epoch, step))[0])
        except Exception:
            self._failover()
            return False
        if verdict == _GO:
            return True
        # re-quorum: the next view may take a moment to publish
        view = View.decode(self._gate_c.get_var("%s#%d" % (_VIEW, epoch + 1)))
        if self.rank not in view.ranks:
            view = self._wait_view_with_me()
        self._adopt(view)
        return False

    def _failover(self):
        """The coordinator stopped answering.  The lowest live rank becomes
        the new coordinator; everyone else rejoins it."""
        logging.warning("[elastic] rank %d: coordinator unreachable — "
                        "failing over", self.rank)
        timeout = float(_flag("elastic_hb_timeout") or 5.0)
        lower_alive = None
        for r in range(self.rank):
            if r >= len(self.members):
                break
            got = self._probe_alive(self.members[r])
            if got is not None:
                lower_alive = r
                break
        if lower_alive is None and self._coord is None:
            self._become_coordinator(epoch=self.epoch + 1,
                                     ranks=[self.rank],
                                     join_window_s=2.0 * timeout)
            self._connect_ctrl(self.rank)
        else:
            coord = (lower_alive if lower_alive is not None
                     else self._find_coordinator())
            self._connect_ctrl(coord)
        view = self._wait_view_with_me()
        self._adopt(view)

    # -- checkpoints ---------------------------------------------------------

    def maybe_save(self, step):
        """Checkpoint from the view's first member only (shared ckpt_dir);
        all members restore the same latest_valid() at re-quorum.  Under a
        sharded zero1 checkpoint every member writes — each rank stages its
        own shard and pid 0 seals the directory (io._write_sharded)."""
        if self.ckpt is None or self.executor is None:
            return None
        sharded = getattr(self.ckpt, "_shard_plan",
                          lambda p: None)(self.main_program)
        if self.pid != 0 and sharded is None:
            return None
        return self.ckpt.maybe_save(self.executor, self.main_program, step)

    # -- teardown ------------------------------------------------------------

    def finalize(self, exit_code=0):
        """Clean completion.  Members leave first (hard-exit right after
        their DONE, so no destructor ever touches a parked client); the
        coordinator waits for every DONE plus a grace period — its process
        holds every epoch's coordination service, so it must be the last
        one whose sockets close (see module docstring).  Does not return
        unless exit_code is None."""
        if self._finalized:
            return
        self._finalized = True
        self._stop_hb.set()
        try:
            self._ctrl.send_var(_DONE + str(self.rank),
                                np.asarray([self.rank], np.int64))
        except Exception:
            pass
        if self._coord is not None:
            self._coord.all_done.wait(timeout=60.0)
            self._coord.stop()
        _tm.event("elastic_finalize", rank=self.rank, epoch=self.epoch)
        _tm.maybe_dump()
        if exit_code is None:
            return
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
        if self._coord is not None or _JaxWorld.hosted_service:
            # members os._exit milliseconds after their DONE lands; ride
            # out interpreter-teardown stragglers before our service
            # sockets vanish under their parked pollers
            time.sleep(2.0)
        # skip interpreter teardown entirely: C++ destructor order would
        # close coordination-service/client sockets under live poll
        # threads -> LOG(FATAL) (client.h:80)
        os._exit(exit_code)
