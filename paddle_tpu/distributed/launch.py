"""Process launcher (port of python/paddle/distributed/launch.py:283).

On GPU the reference spawns one trainer process per device; on TPU one host
process drives all local chips via SPMD, so the launcher spawns one process
per *host* and exports the same env-var scheme
(PADDLE_TRAINER_ID/PADDLE_CURRENT_ENDPOINT/PADDLE_TRAINERS_NUM/
PADDLE_TRAINER_ENDPOINTS).  Multi-host jobs additionally get
PADDLE_COORDINATOR for jax.distributed.initialize.

Usage: python -m paddle_tpu.distributed.launch [--started_port P]
           [--cluster_node_ips ip1,ip2] [--node_ip ip] training_script args...
"""

import argparse
import os
import subprocess
import sys

__all__ = ["launch", "init_multihost"]


def _parse_args(argv=None):
    parser = argparse.ArgumentParser(description="paddle_tpu launcher")
    parser.add_argument("--cluster_node_ips", type=str, default="127.0.0.1")
    parser.add_argument("--node_ip", type=str, default="127.0.0.1")
    parser.add_argument("--started_port", type=int, default=6170)
    parser.add_argument("--print_config", type=bool, default=True)
    parser.add_argument("--selected_tpus", type=str, default=None,
                        help="unused on TPU SPMD (all local chips)")
    parser.add_argument("--selected_gpus", type=str, default=None,
                        help="compat alias, ignored")
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(argv)


def launch(args=None):
    args = args or _parse_args()
    node_ips = [ip.strip() for ip in args.cluster_node_ips.split(",")]
    node_id = node_ips.index(args.node_ip) if args.node_ip in node_ips else 0
    endpoints = ["%s:%d" % (ip, args.started_port) for ip in node_ips]

    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(node_id),
        "PADDLE_CURRENT_ENDPOINT": endpoints[node_id],
        "PADDLE_TRAINERS_NUM": str(len(node_ips)),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_COORDINATOR": endpoints[0],
    })
    cmd = [sys.executable, "-u", args.training_script] + args.training_script_args
    proc = subprocess.Popen(cmd, env=env)
    proc.wait()
    if proc.returncode != 0:
        raise subprocess.CalledProcessError(proc.returncode, cmd)


def init_multihost():
    """Bootstrap jax.distributed from the launcher env (DCN control plane);
    call once at the top of a multi-host training script."""
    n = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
    if n <= 1:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=os.getenv("PADDLE_COORDINATOR"),
        num_processes=n,
        process_id=int(os.getenv("PADDLE_TRAINER_ID", "0")),
    )
    return True


if __name__ == "__main__":
    launch()
