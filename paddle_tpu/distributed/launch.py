"""Process launcher (port of python/paddle/distributed/launch.py:283).

On GPU the reference spawns one trainer process per device; on TPU one host
process drives all local chips via SPMD, so the launcher spawns one process
per *host* and exports the same env-var scheme
(PADDLE_TRAINER_ID/PADDLE_CURRENT_ENDPOINT/PADDLE_TRAINERS_NUM/
PADDLE_TRAINER_ENDPOINTS).  Multi-host jobs additionally get
PADDLE_COORDINATOR for jax.distributed.initialize.

Usage: python -m paddle_tpu.distributed.launch [--started_port P]
           [--cluster_node_ips ip1,ip2] [--node_ip ip] [--restart_failed N]
           [--ckpt_dir DIR] training_script args...

Supervision: ``--restart_failed N`` relaunches the training script up to N
times after a nonzero exit (including death by signal — a SIGKILLed trainer
comes back).  Each incarnation sees PADDLE_RESTART_COUNT in its env (0 for
the first launch), so training scripts can resume from
io.CheckpointManager.latest_valid() instead of step 0 and fault-injection
specs can disarm themselves after the first life.

``--ckpt_dir DIR`` tells the launcher where the supervised script keeps its
rolling checkpoints; before every (re)launch the launcher sweeps the
directory for temp-dir orphans left by a killed writer (the same
``<dir>._tmp.<pid>`` / consumed ``.parts`` rules as
CheckpointManager._gc_stale_tmps) so crash loops cannot accrete disk.  The
relaunched incarnation's own manager GCs too — the launcher sweep just
covers scripts that die before ever constructing one.
"""

import argparse
import atexit
import logging
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "init_multihost"]


def _parse_args(argv=None):
    parser = argparse.ArgumentParser(description="paddle_tpu launcher")
    parser.add_argument("--cluster_node_ips", type=str, default="127.0.0.1")
    parser.add_argument("--node_ip", type=str, default="127.0.0.1")
    parser.add_argument("--started_port", type=int, default=6170)
    parser.add_argument("--print_config", type=bool, default=True)
    parser.add_argument("--selected_tpus", type=str, default=None,
                        help="unused on TPU SPMD (all local chips)")
    parser.add_argument("--selected_gpus", type=str, default=None,
                        help="compat alias, ignored")
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument("--restart_failed", type=int, default=0,
                        help="supervised relaunch: restart the script up "
                             "to N times after a nonzero exit/signal death")
    parser.add_argument("--restart_delay", type=float, default=1.0,
                        help="seconds between supervised relaunches")
    parser.add_argument("--trainer_id", type=int, default=None,
                        help="override the node-ip-derived trainer id "
                             "(single-node multi-process clusters)")
    parser.add_argument("--trainers_num", type=int, default=None,
                        help="override the cluster size when launching "
                             "one member of a larger local cluster")
    parser.add_argument("--ckpt_dir", type=str, default=None,
                        help="checkpoint directory of the supervised "
                             "script; swept for dead-writer temp orphans "
                             "before every (re)launch")
    parser.add_argument("--endpoints_file", type=str, default=None,
                        help="path to a file holding the live cluster view "
                             "(first line: comma-separated trainer "
                             "endpoints; optional second line: coordinator "
                             "endpoint); re-read before every (re)launch so "
                             "a rejoining member sees the post-requorum "
                             "cluster instead of the stale seed one")
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(argv)


def launch(args=None):
    args = args or _parse_args()
    node_ips = [ip.strip() for ip in args.cluster_node_ips.split(",")]
    trainers = (args.trainers_num if args.trainers_num is not None
                else len(node_ips))
    if trainers == len(node_ips):
        endpoints = ["%s:%d" % (ip, args.started_port) for ip in node_ips]
    else:
        # single-node multi-process: one endpoint per trainer on node_ip
        endpoints = ["%s:%d" % (args.node_ip, args.started_port + i)
                     for i in range(trainers)]
    if args.trainer_id is not None:
        node_id = args.trainer_id
    else:
        node_id = (node_ips.index(args.node_ip)
                   if args.node_ip in node_ips else 0)

    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(node_id),
        "PADDLE_CURRENT_ENDPOINT": endpoints[node_id],
        "PADDLE_TRAINERS_NUM": str(trainers),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_COORDINATOR": endpoints[0],
    })
    cmd = ([sys.executable, "-u", args.training_script]
           + args.training_script_args)
    restarts = 0
    while True:
        env["PADDLE_RESTART_COUNT"] = str(restarts)
        _apply_endpoints_file(env, args.endpoints_file, node_id)
        _gc_ckpt_tmps(args.ckpt_dir)
        proc = subprocess.Popen(cmd, env=env, start_new_session=True)
        cleanup = _supervise(proc)
        try:
            proc.wait()
        finally:
            cleanup()
        if proc.returncode == 0:
            return
        if restarts >= max(args.restart_failed, 0):
            raise subprocess.CalledProcessError(proc.returncode, cmd)
        restarts += 1
        logging.warning(
            "training script exited with %s — supervised relaunch %d/%d",
            proc.returncode, restarts, args.restart_failed)
        time.sleep(max(args.restart_delay, 0.0))


def _gc_ckpt_tmps(ckpt_dir):
    """Sweep dead-writer orphans out of ``--ckpt_dir`` before a (re)launch.

    Stdlib-only mirror of CheckpointManager._gc_stale_tmps (the launcher
    deliberately imports neither jax nor the framework): ``<x>._tmp.<pid>``
    entries whose pid is gone, and ``ckpt-<step>.parts`` staging dirs whose
    sealed ``ckpt-<step>`` already exists.  Sealed checkpoints are never
    touched — the relaunched script restores from latest_valid() as usual."""
    import re
    import shutil

    if not ckpt_dir or not os.path.isdir(ckpt_dir):
        return 0

    def _alive(pid):
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except OSError:
            return True
        return True

    removed = 0
    for name in sorted(os.listdir(ckpt_dir)):
        full = os.path.join(ckpt_dir, name)
        m = re.search(r"\._tmp\.(\d+)$", name)
        if m:
            pid = int(m.group(1))
            if pid != os.getpid() and not _alive(pid):
                shutil.rmtree(full, ignore_errors=True)
                if not os.path.isdir(full) and os.path.exists(full):
                    os.remove(full)
                removed += 1
            continue
        if (name.startswith("ckpt-") and name.endswith(".parts")
                and os.path.exists(os.path.join(
                    ckpt_dir, name[:-len(".parts")], "_SUCCESS"))):
            shutil.rmtree(full, ignore_errors=True)
            removed += 1
    if removed:
        logging.warning("swept %d stale checkpoint temp(s) from %s",
                        removed, ckpt_dir)
    return removed


def _apply_endpoints_file(env, path, node_id):
    """Refresh the cluster view from ``--endpoints_file`` before a launch.

    The elastic runtime rewrites this file at every re-quorum, so a member
    relaunched by ``--restart_failed`` rejoins the *current* cluster (new
    coordinator, shrunken endpoint list) instead of the stale seed one."""
    if not path or not os.path.exists(path):
        return
    try:
        with open(path) as f:
            lines = [ln.strip() for ln in f.read().splitlines() if ln.strip()]
    except OSError as e:
        logging.warning("endpoints_file %s unreadable: %s", path, e)
        return
    if not lines:
        return
    endpoints = [ep.strip() for ep in lines[0].split(",") if ep.strip()]
    if not endpoints:
        return
    env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(endpoints)
    env["PADDLE_TRAINERS_NUM"] = str(len(endpoints))
    env["PADDLE_COORDINATOR"] = (lines[1] if len(lines) > 1
                                 else endpoints[0])
    if node_id < len(endpoints):
        env["PADDLE_CURRENT_ENDPOINT"] = endpoints[node_id]


def _supervise(proc):
    """Forward SIGTERM/SIGINT to the supervised child's process group and
    killpg it if the launcher itself dies, so a terminated launcher cannot
    orphan a trainer.  Returns a callable undoing the handlers."""

    def _killpg(sig):
        try:
            os.killpg(proc.pid, sig)
        except (ProcessLookupError, PermissionError, OSError):
            pass

    def _forward(signum, _frame):
        _killpg(signum)

    def _reap():
        if proc.poll() is None:
            _killpg(signal.SIGKILL)

    prev = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev[sig] = signal.signal(sig, _forward)
        except (ValueError, OSError):  # non-main thread
            pass
    atexit.register(_reap)

    def _cleanup():
        atexit.unregister(_reap)
        for sig, handler in prev.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):
                pass

    return _cleanup


def init_multihost():
    """Bootstrap jax.distributed from the launcher env (DCN control plane);
    call once at the top of a multi-host training script."""
    n = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
    if n <= 1:
        return False
    import jax

    if os.getenv("JAX_PLATFORMS", "").startswith("cpu"):
        # cross-process CPU collectives need the gloo transport; without it
        # XLA rejects multiprocess computations on the CPU backend
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=os.getenv("PADDLE_COORDINATOR"),
        num_processes=n,
        process_id=int(os.getenv("PADDLE_TRAINER_ID", "0")),
    )
    return True


if __name__ == "__main__":
    launch()
