"""Graph IR: Program / Block / Operator / Variable.

TPU-native re-design of the reference's static-graph IR
(``paddle/fluid/framework/framework.proto`` + ``python/paddle/fluid/framework.py``,
Variable at framework.py:561, Operator at :1680, Block at :2132, Program at :3515).

Unlike the reference there is no protobuf/C++ desc split: the Python objects ARE
the IR, and execution lowers whole blocks into a single jitted XLA computation
(see ``paddle_tpu.core.executor``).  Serialization is JSON (see ``to_dict``).
"""

import contextlib
import itertools
import threading
import copy
import json

import numpy as np

from .utils import unique_name

__all__ = [
    "Program",
    "Block",
    "Operator",
    "Variable",
    "Parameter",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "name_scope",
    "cpu_places",
    "in_dygraph_mode",
    "convert_np_dtype_to_dtype_",
    "core",
]

# ---------------------------------------------------------------------------
# dtypes — canonical form is a numpy dtype string ('float32', ...), with
# 'bfloat16' handled specially (jax.numpy dtype).
# ---------------------------------------------------------------------------

_SUPPORTED_DTYPES = (
    "bool",
    "int8",
    "uint8",
    "int16",
    "int32",
    "int64",
    "float16",
    "bfloat16",
    "float32",
    "float64",
)


def convert_np_dtype_to_dtype_(dtype):
    """Normalize a dtype spec (numpy dtype / str / jnp dtype) to a str name."""
    if dtype is None:
        return None
    name = getattr(dtype, "name", None)
    if name is None:
        if isinstance(dtype, str):
            name = dtype
        else:
            name = np.dtype(dtype).name
    if name == "bfloat16" or "bfloat16" in str(dtype):
        return "bfloat16"
    if name not in _SUPPORTED_DTYPES:
        raise TypeError("unsupported dtype: %r" % (dtype,))
    return name


def dtype_to_np(dtype):
    if dtype == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return np.dtype(dtype)


# ---------------------------------------------------------------------------
# Var types (subset of framework.proto VarType, framework.proto:105)
# ---------------------------------------------------------------------------


class VarTypes:
    LOD_TENSOR = "lod_tensor"
    SELECTED_ROWS = "selected_rows"
    LOD_TENSOR_ARRAY = "lod_tensor_array"
    READER = "reader"
    STEP_SCOPES = "step_scopes"
    RAW = "raw"


# Op role annotation protocol (reference: op_proto_maker.h:26-48).  Backward
# and the distributed transpilers key off these.
class OpRole:
    Forward = 0
    Backward = 1
    Optimize = 2
    RPC = 3
    Dist = 4
    LRSched = 16
    Loss = 256

OP_ROLE_KEY = "op_role"
OP_ROLE_VAR_KEY = "op_role_var"


# ---------------------------------------------------------------------------
# Places. TPUPlace is the native device; CUDAPlace is provided as a
# compatibility alias so `fluid.CUDAPlace -> fluid.TPUPlace` swaps are the
# only user-visible change (reference: platform/place.h:26-79).
# ---------------------------------------------------------------------------


class Place:
    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self.device_id)

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def jax_device(self):
        import jax

        kind = "cpu" if isinstance(self, CPUPlace) else None
        # process-LOCAL devices: under multi-controller jax (nccl2-mode
        # analog) eager values and single-device programs must live on a
        # device this process addresses, never on another host's
        devs = jax.local_devices(backend=kind) if kind else jax.local_devices()
        if kind is None:
            # prefer an accelerator backend if present
            try:
                accel = [d for d in devs if d.platform != "cpu"]
                if accel:
                    devs = accel
            except Exception:
                pass
        return devs[self.device_id % len(devs)]


class CPUPlace(Place):
    def __init__(self):
        super().__init__(0)


class TPUPlace(Place):
    pass


class CUDAPlace(TPUPlace):
    """Compatibility alias: maps to the TPU device."""


class CUDAPinnedPlace(CPUPlace):
    pass


def cpu_places(device_count=None):
    return [CPUPlace()]


def tpu_places(device_ids=None):
    import jax

    n = len(jax.devices())
    ids = range(n) if device_ids is None else device_ids
    return [TPUPlace(i) for i in ids]


cuda_places = tpu_places


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return True


def require_version(min_version, max_version=None):
    """Raise unless the installed version is within [min_version,
    max_version] (reference framework.py:66).  Version strings are 1-4
    dot-separated integers; missing components compare as 0."""
    import re as _re

    if not isinstance(min_version, str):
        raise TypeError(
            "The type of 'min_version' in require_version must be str, but "
            "received %s." % type(min_version))
    if not isinstance(max_version, (str, type(None))):
        raise TypeError(
            "The type of 'max_version' in require_version must be str or "
            "type(None), but received %s." % type(max_version))

    def parse(ver, arg):
        m = _re.match(r"\d+(\.\d+){0,3}", ver)
        if m is None or m.group() != ver:
            raise ValueError(
                "The value of '%s' in require_version must be in format "
                "'\\d+(\\.\\d+){0,3}', like '1.5.2.0', but received %s"
                % (arg, ver))
        parts = [int(p) for p in ver.split(".")]
        return parts + [0] * (4 - len(parts))

    lo = parse(min_version, "min_version")
    hi = parse(max_version, "max_version") if max_version is not None else None
    from . import __version__ as _v

    m = _re.match(r"\d+(\.\d+){0,3}", _v)
    if m is None:
        # dev/rc build with no leading numeric component: reference warns
        # and accepts rather than blaming the caller's argument
        import warnings

        warnings.warn(
            "paddle_tpu version %s or higher is required, but a development "
            "version (%s) is installed; please make sure the version is "
            "good with your code." % (min_version, _v))
        return
    parts = [int(p) for p in m.group().split(".")]
    installed = parts + [0] * (4 - len(parts))
    if installed < lo or (hi is not None and installed > hi):
        raise Exception(
            "VersionError: paddle_tpu version %s does not satisfy the "
            "requirement [%s, %s]" % (_v, min_version, max_version or "any"))


def load_op_library(lib_filename):
    """Reference framework.py:4772 loads a .so of custom C++ OpKernels and
    refreshes the proto registry.  TPU custom ops are Python/Pallas
    lowerings registered through core.registry.register_op instead; a
    shared library of CUDA kernels cannot be mapped onto the XLA path, so
    this raises with the supported alternative spelled out."""
    raise NotImplementedError(
        "load_op_library(%r): custom ops on the TPU backend are added with "
        "paddle_tpu.core.registry.register_op (a JAX/Pallas lowering), not "
        "a dynamic library of CUDA kernels" % (lib_filename,))


# ---------------------------------------------------------------------------
# Variable
# ---------------------------------------------------------------------------


class Variable:
    """A node in a Block's symbol table (reference framework.py:561).

    Holds static metadata only (shape may contain -1 for the batch dim);
    values live in a Scope at run time.
    """

    def __init__(
        self,
        block,
        name=None,
        shape=None,
        dtype=None,
        lod_level=0,
        persistable=False,
        stop_gradient=False,
        type=VarTypes.LOD_TENSOR,
        is_data=False,
        need_check_feed=False,
        initializer=None,
        **kwargs,
    ):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = convert_np_dtype_to_dtype_(dtype) if dtype is not None else None
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.type = type
        self.is_data = is_data
        self.need_check_feed = need_check_feed
        # Optional jax.sharding.PartitionSpec-like annotation (tuple of axis
        # names / None) consumed by the executor for TP/DP layouts.
        self.sharding = kwargs.get("sharding", None)
        self.initializer = initializer
        # dygraph (eager) mode: concrete jax.Array value + accumulated grad
        # (analog of imperative::VarBase, paddle/fluid/imperative/layer.h:55)
        self._ivar = None
        self._grad_ivar = None

    # -- api parity helpers --------------------------------------------------
    def numpy(self, scope=None):
        from .core.executor import global_scope

        if self._ivar is not None:
            return np.asarray(self._ivar)
        scope = scope or global_scope()
        var = scope.find_var(self.name)
        if var is None:
            raise RuntimeError("variable %s has no value in scope" % self.name)
        return np.asarray(var.get_tensor())

    def set_value(self, value, scope=None):
        from .core.executor import global_scope

        scope = scope or global_scope()
        scope.var(self.name).set(value)

    @property
    def grad_name(self):
        return _grad_var_name(self.name)

    # -- dygraph autograd ----------------------------------------------------
    def backward(self, backward_strategy=None, retain_graph=False):
        if not in_dygraph_mode():
            raise RuntimeError(
                "Variable.backward() only works in dygraph mode; use "
                "append_backward/Optimizer.minimize for static graphs"
            )
        from .dygraph import engine

        engine.run_backward(_dygraph_tracer(), self, retain_graph=retain_graph)

    def gradient(self):
        if self._grad_ivar is None:
            return None
        return np.asarray(self._grad_ivar)

    def clear_gradient(self):
        self._grad_ivar = None

    def astype(self, dtype):
        from . import layers

        return layers.cast(self, dtype)

    def __repr__(self):
        return "Variable(name=%s, shape=%s, dtype=%s%s)" % (
            self.name,
            self.shape,
            self.dtype,
            ", persistable" if self.persistable else "",
        )

    __str__ = __repr__

    def to_dict(self):
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "lod_level": self.lod_level,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "type": self.type,
            "is_data": self.is_data,
            "is_parameter": isinstance(self, Parameter),
            "trainable": getattr(self, "trainable", None),
            "sharding": list(self.sharding) if self.sharding else None,
        }


GRAD_SUFFIX = "@GRAD"


def _grad_var_name(name):
    return name + GRAD_SUFFIX


class Parameter(Variable):
    """A trainable persistable variable (reference framework.py:5157)."""

    def __init__(self, block, shape, dtype, **kwargs):
        kwargs.setdefault("persistable", True)
        self.trainable = kwargs.pop("trainable", True)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.pop("regularizer", None)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        self.is_distributed = kwargs.pop("is_distributed", False)
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------


def _as_varname_list(block, v):
    """Normalize an input/output slot value to a list of var names."""
    if v is None:
        return []
    if isinstance(v, (list, tuple)):
        return [_single_varname(block, x) for x in v]
    return [_single_varname(block, v)]


def _single_varname(block, v):
    if isinstance(v, Variable):
        return v.name
    if isinstance(v, str):
        return v
    raise TypeError("expected Variable or str, got %r" % (v,))


class Operator:
    """One op in a block (reference framework.py:1680).

    inputs/outputs map slot name -> list of variable names. attrs is a plain
    dict (JSON-serializable values only).
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs = {
            k: _as_varname_list(block, v) for k, v in (inputs or {}).items()
        }
        self.outputs = {
            k: _as_varname_list(block, v) for k, v in (outputs or {}).items()
        }
        self.attrs = dict(attrs or {})
        self.attrs.setdefault(OP_ROLE_KEY, _current_role())

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for names in self.inputs.values() for n in names]

    @property
    def output_arg_names(self):
        return [n for names in self.outputs.values() for n in names]

    def attr(self, name):
        return self.attrs.get(name)

    def _set_attr(self, name, val):
        self.attrs[name] = val

    def has_attr(self, name):
        return name in self.attrs

    def __repr__(self):
        return "{%s: inputs=%s outputs=%s}" % (self.type, self.inputs, self.outputs)

    def to_dict(self):
        attrs = {}
        for k, v in self.attrs.items():
            if isinstance(v, np.ndarray):
                v = v.tolist()
            if isinstance(v, (np.integer,)):
                v = int(v)
            if isinstance(v, (np.floating,)):
                v = float(v)
            attrs[k] = v
        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": attrs,
        }


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------


class Block:
    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}  # name -> Variable
        self.ops = []  # list[Operator]

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    # -- vars ---------------------------------------------------------------
    def create_var(self, **kwargs):
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        return var

    def create_parameter(self, **kwargs):
        shape = kwargs.pop("shape")
        dtype = kwargs.pop("dtype")
        param = Parameter(self, shape, dtype, **kwargs)
        # parameters always live in the top-level (global) block's symbol table
        gblock = self.program.global_block()
        gblock.vars[param.name] = param
        if self is not gblock:
            self.vars[param.name] = param
        if in_dygraph_mode():
            param.stop_gradient = not param.trainable
            _dygraph_tracer().track_parameter(param)
        return param

    def var(self, name):
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError("variable %r not found in block %d" % (name, self.idx))
        return v

    def has_var(self, name):
        return name in self.vars

    def has_var_recursive(self, name):
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- ops ----------------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        from .core.registry import get_op_def

        if in_dygraph_mode():
            # eager dispatch: execute the op's lowering immediately; no op is
            # appended to the block (tracer.cc:82 TraceOp analog)
            return _dygraph_tracer().trace_op(self, type, inputs, outputs,
                                              attrs)
        op = Operator(self, type, inputs, outputs, attrs)
        opdef = get_op_def(type)  # raises for unknown op types
        if opdef is not None:
            opdef.validate(op)
        self.ops.append(op)
        self.program._bump_version()
        # static shape/dtype inference for outputs lacking metadata
        if opdef is not None:
            opdef.run_infer_shape(op, self)
        return op

    def _insert_op(self, index, type, inputs=None, outputs=None, attrs=None):
        from .core.registry import get_op_def

        op = Operator(self, type, inputs, outputs, attrs)
        opdef = get_op_def(type)
        if opdef is not None:
            opdef.validate(op)
        self.ops.insert(index, op)
        self.program._bump_version()
        if opdef is not None:
            opdef.run_infer_shape(op, self)
        return op

    def _remove_op(self, index):
        del self.ops[index]
        self.program._bump_version()

    def prepend_op(self, **kwargs):
        return self._insert_op(0, **kwargs)

    def __repr__(self):
        lines = ["Block %d (parent %d):" % (self.idx, self.parent_idx)]
        for v in self.vars.values():
            lines.append("  " + repr(v))
        for op in self.ops:
            lines.append("  " + repr(op))
        return "\n".join(lines)

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }


# ---------------------------------------------------------------------------
# Program
# ---------------------------------------------------------------------------


class Program:
    """A whole model: list of blocks, block 0 is global (reference framework.py:3515)."""

    _uid_counter = itertools.count()

    def __init__(self):
        # monotonic process-wide UID: executor caches key on this instead of
        # id(program), which a GC'd Program's successor can alias
        self._uid = next(Program._uid_counter)
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        self._op_role = OpRole.Forward
        self._op_role_var = []
        # executor hints
        self._is_test = False
        self._sharding_mesh = None
        # non-iterable DataLoaders attached to this program (reader.py):
        # exe.run(feed=None) pulls batches from the first started one
        self._attached_loaders = []

    # -- version (invalidates executor caches) ------------------------------
    def _bump_version(self):
        self._version += 1

    @property
    def version(self):
        return self._version

    # -- blocks -------------------------------------------------------------
    def global_block(self):
        return self.blocks[0]

    def block(self, idx):
        return self.blocks[idx]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx=None):
        parent = self.current_block_idx if parent_idx is None else parent_idx
        blk = Block(self, len(self.blocks), parent)
        self.blocks.append(blk)
        self.current_block_idx = blk.idx
        self._bump_version()
        return blk

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    # -- op role protocol ----------------------------------------------------
    @contextlib.contextmanager
    def _optimized_guard(self, param_and_grads):
        old_role, old_var = self._op_role, self._op_role_var
        self._op_role = OpRole.Optimize
        self._op_role_var = [
            v.name if isinstance(v, Variable) else v for v in param_and_grads
        ]
        try:
            yield
        finally:
            self._op_role, self._op_role_var = old_role, old_var

    @contextlib.contextmanager
    def _backward_role_guard(self):
        old_role = self._op_role
        self._op_role = OpRole.Backward
        try:
            yield
        finally:
            self._op_role = old_role

    @contextlib.contextmanager
    def _lr_schedule_guard(self):
        old_role = self._op_role
        self._op_role = OpRole.LRSched
        try:
            yield
        finally:
            self._op_role = old_role

    # -- cloning / pruning ---------------------------------------------------
    def clone(self, for_test=False):
        p = Program()
        p.blocks = []
        for blk in self.blocks:
            nb = Block(p, blk.idx, blk.parent_idx)
            for name, v in blk.vars.items():
                if isinstance(v, Parameter):
                    nv = Parameter(
                        nb,
                        v.shape,
                        v.dtype,
                        name=v.name,
                        trainable=v.trainable,
                        regularizer=v.regularizer,
                        optimize_attr=dict(v.optimize_attr),
                        stop_gradient=v.stop_gradient,
                        initializer=v.initializer,
                        sharding=v.sharding,
                    )
                else:
                    nv = Variable(
                        nb,
                        name=v.name,
                        shape=v.shape,
                        dtype=v.dtype,
                        lod_level=v.lod_level,
                        persistable=v.persistable,
                        stop_gradient=v.stop_gradient,
                        type=v.type,
                        is_data=v.is_data,
                        initializer=v.initializer,
                        sharding=v.sharding,
                    )
                nb.vars[name] = nv
            for op in blk.ops:
                nop = Operator(
                    nb,
                    op.type,
                    {k: list(v) for k, v in op.inputs.items()},
                    {k: list(v) for k, v in op.outputs.items()},
                    copy.deepcopy(op.attrs),
                )
                nb.ops.append(nop)
            p.blocks.append(nb)
        p.current_block_idx = 0
        p.random_seed = self.random_seed
        p._is_test = for_test
        if for_test:
            for blk in p.blocks:
                for op in blk.ops:
                    if "is_test" in op.attrs:
                        op.attrs["is_test"] = True
                    if op.type == "dropout":
                        op.attrs["is_test"] = True
        p._bump_version()
        return p

    def list_vars(self):
        for blk in self.blocks:
            for v in blk.vars.values():
                yield v

    # -- serialization -------------------------------------------------------
    def to_dict(self):
        return {
            "version": 1,
            "random_seed": self.random_seed,
            "blocks": [b.to_dict() for b in self.blocks],
        }

    def to_string(self, throw_on_error=False, with_details=False):
        return json.dumps(self.to_dict(), indent=1)

    __str__ = to_string

    @staticmethod
    def from_dict(d):
        p = Program()
        p.random_seed = d.get("random_seed", 0)
        p.blocks = []
        for bd in d["blocks"]:
            blk = Block(p, bd["idx"], bd["parent_idx"])
            for vd in bd["vars"]:
                cls = Parameter if vd.get("is_parameter") else Variable
                kwargs = dict(
                    name=vd["name"],
                    lod_level=vd.get("lod_level", 0),
                    persistable=vd.get("persistable", False),
                    stop_gradient=vd.get("stop_gradient", False),
                    type=vd.get("type", VarTypes.LOD_TENSOR),
                    is_data=vd.get("is_data", False),
                )
                if vd.get("sharding"):
                    kwargs["sharding"] = tuple(vd["sharding"])
                shape = tuple(vd["shape"]) if vd.get("shape") is not None else None
                if cls is Parameter:
                    v = Parameter(blk, shape, vd["dtype"], **kwargs)
                else:
                    v = Variable(blk, shape=shape, dtype=vd["dtype"], **kwargs)
                blk.vars[v.name] = v
            for od in bd["ops"]:
                op = Operator(blk, od["type"], od["inputs"], od["outputs"], od["attrs"])
                blk.ops.append(op)
            p.blocks.append(blk)
        p._bump_version()
        return p


# ---------------------------------------------------------------------------
# default programs / guards
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()

# Per-thread default-program overrides: concurrent builder threads (e.g.
# pserver/worker role threads standing in for the reference's separate
# processes, test harnesses) must not race on the global defaults.  The
# MAIN thread keeps using the module globals so that programs built in the
# main thread remain visible to helper threads that never called
# program_guard themselves (trainer feed threads, pipeline sections).
_prog_tls = threading.local()


def _is_main_thread():
    return threading.current_thread() is threading.main_thread()


def default_main_program():
    if not _is_main_thread() and getattr(_prog_tls, "main", None) is not None:
        return _prog_tls.main
    return _main_program


def default_startup_program():
    if not _is_main_thread() and getattr(_prog_tls, "startup", None) is not None:
        return _prog_tls.startup
    return _startup_program


def switch_main_program(program):
    global _main_program
    if _is_main_thread():
        old = _main_program
        _main_program = program
    else:
        old = getattr(_prog_tls, "main", None)
        _prog_tls.main = program
    return old


def switch_startup_program(program):
    global _startup_program
    if _is_main_thread():
        old = _startup_program
        _startup_program = program
    else:
        old = getattr(_prog_tls, "startup", None)
        _prog_tls.startup = program
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


@contextlib.contextmanager
def name_scope(prefix=None):
    # cosmetic only (reference framework.py name_scope); kept for API parity
    yield


def _current_role():
    prog = default_main_program()
    return prog._op_role if prog else OpRole.Forward


# ---------------------------------------------------------------------------
# dygraph mode switch (implemented in paddle_tpu.dygraph)
# ---------------------------------------------------------------------------

_dygraph_tracer_ = None


def in_dygraph_mode():
    return _dygraph_tracer_ is not None


def _dygraph_tracer():
    return _dygraph_tracer_


# ---------------------------------------------------------------------------
# `core` compatibility shim: a handful of symbols user code expects on
# fluid.core in the reference (pybind module).
# ---------------------------------------------------------------------------


class _CoreShim:
    CPUPlace = CPUPlace
    TPUPlace = TPUPlace
    CUDAPlace = CUDAPlace
    VarDesc = None

    @staticmethod
    def is_compiled_with_cuda():
        return False

    # NOTE: fluid.core resolves to the paddle_tpu.core package (the
    # submodule import rebinds the attribute after this shim); the pybind
    # aliases (LoDTensor, LoDTensorArray, Scope) live in core/__init__.py
    # only, so there is a single alias table.


core = _CoreShim()
