"""Initializers — emit init ops into the startup program.

Parity: python/paddle/fluid/initializer.py (Constant/Uniform/Normal/
TruncatedNormal/Xavier/MSRA/Bilinear/NumpyArray).
"""

import math

import numpy as np

from .framework import default_startup_program
from .ops.common import dtype_enum

__all__ = [
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "Xavier",
    "MSRA",
    "Bilinear",
    "NumpyArrayInitializer",
    "ConstantInitializer",
    "UniformInitializer",
    "NormalInitializer",
    "TruncatedNormalInitializer",
    "XavierInitializer",
    "MSRAInitializer",
    "BilinearInitializer",
    "force_init_on_cpu",
]


def force_init_on_cpu():
    return False


class Initializer:
    def __call__(self, var, block=None):
        raise NotImplementedError

    def _startup_block(self, block):
        if block is not None:
            return block
        return default_startup_program().global_block()

    def _declare(self, var, block):
        """Mirror the var into the startup block so the init op validates."""
        if not block.has_var(var.name):
            block.create_var(
                name=var.name,
                shape=var.shape,
                dtype=var.dtype,
                persistable=var.persistable,
            )


    def _resolve_seed(self, var, block):
        """Reference behavior (framework.py): a zero op seed falls back to
        block.program.random_seed.  We additionally key it by the op's
        emission position (the reference reuses the bare program seed, so
        same-shape params get identical draws — a known fluid quirk this
        avoids).  The resolved value is MATERIALIZED into the op attr, so:
        - rebuilding the same model in-process reproduces it (emission
          order is deterministic, unique-name counters don't matter), and
        - the PS transpiler's pserver startup (a clone of these ops,
          ps_transpile.py startup_for) carries the same seeds across
          processes."""
        if getattr(self, "_seed", 0):
            return self._seed
        prog_seed = getattr(block.program, "random_seed", 0) or 0
        if prog_seed:
            return ((prog_seed * 1000003 + len(block.ops) + 1)
                    & 0x7FFFFFFF) or 1  # 0 would mean 'unseeded' to the op
        return 0


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self._value = value

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(var, block)
        return block.append_op(
            type="fill_constant",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": dtype_enum(var.dtype),
                "value": float(self._value),
            },
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self._low, self._high, self._seed = low, high, seed

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(var, block)
        return block.append_op(
            type="uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": dtype_enum(var.dtype),
                "min": self._low,
                "max": self._high,
                "seed": self._resolve_seed(var, block),
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(var, block)
        return block.append_op(
            type="gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": dtype_enum(var.dtype),
                "mean": self._mean,
                "std": self._std,
                "seed": self._resolve_seed(var, block),
            },
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(var, block)
        return block.append_op(
            type="truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": dtype_enum(var.dtype),
                "mean": self._mean,
                "std": self._std,
                "seed": self._resolve_seed(var, block),
            },
        )


def _fan_in_out(var):
    shape = var.shape
    if len(shape) < 2:
        return int(shape[0]) if shape else 1, int(shape[0]) if shape else 1
    receptive = 1
    for d in shape[2:]:
        receptive *= int(d)
    fan_in = int(shape[0]) * receptive if len(shape) > 2 else int(shape[0])
    fan_out = int(shape[1]) * receptive if len(shape) > 2 else int(shape[1])
    # conv weights are [out_c, in_c, kh, kw] in fluid layout
    if len(shape) > 2:
        fan_in = int(shape[1]) * receptive
        fan_out = int(shape[0]) * receptive
    return fan_in, fan_out


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self._uniform = uniform
        self._fan_in, self._fan_out = fan_in, fan_out
        self._seed = seed

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(var, block)
        fi, fo = _fan_in_out(var)
        fan_in = self._fan_in if self._fan_in is not None else fi
        fan_out = self._fan_out if self._fan_out is not None else fo
        if self._uniform:
            limit = math.sqrt(6.0 / (fan_in + fan_out))
            return block.append_op(
                type="uniform_random",
                outputs={"Out": [var.name]},
                attrs={
                    "shape": list(var.shape),
                    "dtype": dtype_enum(var.dtype),
                    "min": -limit,
                    "max": limit,
                    "seed": self._resolve_seed(var, block),
                },
            )
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return block.append_op(
            type="gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": dtype_enum(var.dtype),
                "mean": 0.0,
                "std": std,
                "seed": self._resolve_seed(var, block),
            },
        )


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self._uniform, self._fan_in, self._seed = uniform, fan_in, seed

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(var, block)
        fi, _ = _fan_in_out(var)
        fan_in = self._fan_in if self._fan_in is not None else fi
        if self._uniform:
            limit = math.sqrt(6.0 / fan_in)
            attrs = {"min": -limit, "max": limit}
            op_type = "uniform_random"
        else:
            attrs = {"mean": 0.0, "std": math.sqrt(2.0 / fan_in)}
            op_type = "gaussian_random"
        attrs.update(
            shape=list(var.shape), dtype=dtype_enum(var.dtype),
            seed=self._resolve_seed(var, block),
        )
        return block.append_op(
            type=op_type, outputs={"Out": [var.name]}, attrs=attrs
        )


class BilinearInitializer(Initializer):
    """For upsampling deconv weights (initializer.py:Bilinear)."""

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(var, block)
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype="float32")
        size = shape[2] * shape[3]
        for i in np.arange(np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            idx = np.unravel_index(int(i), shape)
            weight[idx] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return NumpyArrayInitializer(weight)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self._value = np.asarray(value)

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(var, block)
        v = self._value
        key = {
            "float32": "fp32_values",
            "float64": "fp32_values",
            "int32": "int32_values",
            "int64": "int64_values",
            "bool": "bool_values",
        }.get(var.dtype, "fp32_values")
        return block.append_op(
            type="assign_value",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(v.shape),
                "dtype": dtype_enum(var.dtype),
                key: [float(x) if "fp" in key else int(x) for x in v.flatten()],
            },
        )


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
