"""Synthetic Flowers-102 (python/paddle/dataset/flowers.py interface:
train/test/valid).  Yields (chw float32 image [3,32,32] in [0,1],
int64 label in [0,102))."""

import itertools

import numpy as np

CLASSES = 102
SHAPE = (3, 32, 32)
TRAIN_SIZE = 2048
TEST_SIZE = 512
VALID_SIZE = 512


def _reader(n, seed, cycle=False):
    def reader():
        rng0 = np.random.RandomState(77)
        tpl = rng0.uniform(0, 1, size=(CLASSES,) + SHAPE).astype("float32")
        rng = np.random.RandomState(seed)
        it = itertools.count() if cycle else range(n)
        for _ in it:
            y = int(rng.randint(0, CLASSES))
            x = tpl[y] + 0.2 * rng.randn(*SHAPE).astype("float32")
            yield np.clip(x, 0, 1).astype("float32"), np.int64(y)

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader(TRAIN_SIZE, seed=21, cycle=cycle)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader(TEST_SIZE, seed=22, cycle=cycle)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader(VALID_SIZE, seed=23)


def fetch():
    pass
