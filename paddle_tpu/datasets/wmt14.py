"""Synthetic WMT14 en-fr (python/paddle/dataset/wmt14.py interface:
train/test/gen/get_dict).  Deterministic translation rule (id shift with a
reversal, like the wmt16 module) so seq2seq models can learn it.  Samples
are (src_ids, trg_ids_with_<s>, trg_next_ids) per the reference reader."""

import numpy as np

START_ID, END_ID, UNK_ID = 0, 1, 2
TRAIN_SIZE = 2048
TEST_SIZE = 256
MIN_LEN, MAX_LEN = 4, 16


def _dicts(dict_size):
    src = {"<s>": 0, "<e>": 1, "<unk>": 2}
    trg = dict(src)
    for i in range(3, dict_size):
        src["s%d" % i] = i
        trg["t%d" % i] = i
    return src, trg


def get_dict(dict_size, reverse=True):
    src, trg = _dicts(dict_size)
    if reverse:
        return ({v: k for k, v in src.items()},
                {v: k for k, v in trg.items()})
    return src, trg


def _translate(src_ids, dict_size):
    # target = reversed source shifted by 3 (mod usable vocab)
    usable = dict_size - 3
    return [3 + ((i - 3 + 7) % usable) for i in reversed(src_ids)]


def _reader(n, seed, dict_size):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            ln = int(rng.randint(MIN_LEN, MAX_LEN + 1))
            src = [int(v) for v in rng.randint(3, dict_size, ln)]
            trg = _translate(src, dict_size)
            yield (src, [START_ID] + trg, trg + [END_ID])

    return reader


def train(dict_size):
    return _reader(TRAIN_SIZE, 51, dict_size)


def test(dict_size):
    return _reader(TEST_SIZE, 52, dict_size)


def gen(dict_size):
    return _reader(TEST_SIZE, 53, dict_size)


def fetch():
    pass
