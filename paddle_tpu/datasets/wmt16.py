"""Synthetic WMT16 (python/paddle/dataset/wmt16.py interface): a
deterministic "translation" corpus where the target is a learnable
transformation of the source (token shift + reversal), exercising the full
variable-length seq2seq path.  Readers yield (src_ids, trg_ids, trg_next)
with <s>=0, <e>=1, <unk>=2 like the reference."""

import numpy as np

BOS, EOS, UNK = 0, 1, 2
RESERVED = 3


def _reader(n, seed, src_vocab_size, trg_vocab_size, min_len=4, max_len=16):
    def reader():
        rng = np.random.RandomState(seed)
        usable = min(src_vocab_size, trg_vocab_size) - RESERVED
        for _ in range(n):
            ln = int(rng.randint(min_len, max_len + 1))
            src = rng.randint(0, usable, size=ln)
            # target: reversed source with a +1 shift (mod usable vocab)
            trg = (src[::-1] + 1) % usable
            src_ids = (src + RESERVED).astype("int64").tolist()
            trg_full = [BOS] + (trg + RESERVED).astype("int64").tolist() + [EOS]
            yield src_ids, trg_full[:-1], trg_full[1:]

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en", min_len=4, max_len=16):
    return _reader(4096, 11, src_dict_size, trg_dict_size, min_len, max_len)


def test(src_dict_size, trg_dict_size, src_lang="en", min_len=4, max_len=16):
    return _reader(512, 12, src_dict_size, trg_dict_size, min_len, max_len)


def validation(src_dict_size, trg_dict_size, src_lang="en", min_len=4,
               max_len=16):
    return _reader(512, 13, src_dict_size, trg_dict_size, min_len, max_len)
