"""Synthetic UCI housing (python/paddle/dataset/uci_housing.py interface):
linear regression data with fixed ground-truth weights.  Readers yield
(features[13] float32, price[1] float32)."""

import numpy as np

FEATURE_DIM = 13
TRAIN_SIZE = 404
TEST_SIZE = 102


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        w = np.random.RandomState(7).randn(FEATURE_DIM).astype("float32")
        for _ in range(n):
            x = rng.randn(FEATURE_DIM).astype("float32")
            y = x @ w + 0.1 * rng.randn()
            yield x, np.array([y], dtype="float32")

    return reader


def train():
    return _reader(TRAIN_SIZE, seed=3)


def test():
    return _reader(TEST_SIZE, seed=4)
