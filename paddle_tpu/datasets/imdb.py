"""Synthetic IMDB sentiment (python/paddle/dataset/imdb.py interface):
variable-length token-id sequences whose class-conditional token
distributions differ, so bag-of-words/rnn models can learn.  Readers yield
(word_ids list[int64], label int64 in {0,1})."""

import numpy as np

VOCAB_SIZE = 5149  # reference imdb word_dict size ballpark
TRAIN_SIZE = 2048
TEST_SIZE = 512
MIN_LEN, MAX_LEN = 8, 100


def word_dict():
    return {("w%d" % i).encode(): i for i in range(VOCAB_SIZE)}


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        half = VOCAB_SIZE // 2
        for _ in range(n):
            y = int(rng.randint(0, 2))
            ln = int(rng.randint(MIN_LEN, MAX_LEN + 1))
            # positive reviews skew to the lower half of the vocab
            lo, hi = (0, half + half // 2) if y else (half - half // 2, VOCAB_SIZE)
            ids = rng.randint(lo, hi, size=ln).astype("int64")
            yield list(ids), np.int64(y)

    return reader


def train(word_idx=None):
    return _reader(TRAIN_SIZE, seed=5)


def test(word_idx=None):
    return _reader(TEST_SIZE, seed=6)
