"""Synthetic Pascal VOC2012 segmentation (python/paddle/dataset/voc2012.py
interface: train/test/val).  Yields (chw float32 image [3,H,W],
int64 label map [H,W] with 21 classes)."""

import numpy as np

CLASSES = 21
H = W = 64
TRAIN_SIZE = 256
TEST_SIZE = 64
VAL_SIZE = 64


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            # blocky class regions: image intensity encodes the class, so a
            # per-pixel classifier can learn the mapping
            label = np.zeros((H, W), "int64")
            img = np.zeros((3, H, W), "float32")
            for _k in range(4):
                c = int(rng.randint(0, CLASSES))
                y0, x0 = rng.randint(0, H // 2), rng.randint(0, W // 2)
                hh, ww = rng.randint(8, H // 2), rng.randint(8, W // 2)
                label[y0:y0 + hh, x0:x0 + ww] = c
                img[:, y0:y0 + hh, x0:x0 + ww] = c / float(CLASSES)
            img += 0.05 * rng.randn(3, H, W).astype("float32")
            yield np.clip(img, 0, 1).astype("float32"), label

    return reader


def train():
    return _reader(TRAIN_SIZE, 41)


def test():
    return _reader(TEST_SIZE, 42)


def val():
    return _reader(VAL_SIZE, 43)


def fetch():
    pass
