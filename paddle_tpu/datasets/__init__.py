"""Dataset corpora package (python/paddle/dataset analog).

The reference downloads real corpora (mnist, uci_housing, imdb, wmt16…).
This environment has zero network egress, so each module synthesizes a
deterministic, learnable stand-in corpus with the same reader interface
(nullary callables yielding samples) — the pipeline, batching, and model
code paths are identical to the reference's.
"""

from . import cifar  # noqa: F401
from . import conll05  # noqa: F401
from . import flowers  # noqa: F401
from . import image  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import mnist  # noqa: F401
from . import movielens  # noqa: F401
from . import mq2007  # noqa: F401
from . import sentiment  # noqa: F401
from . import uci_housing  # noqa: F401
from . import voc2012  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
