"""Synthetic CIFAR (python/paddle/dataset/cifar.py interface: train10/
test10/train100/test100).  Class-templated 3x32x32 images, flattened
float32 in [0,1] + int64 label, like the reference readers."""

import itertools

import numpy as np

DIM = 3 * 32 * 32
TRAIN_SIZE = 4096
TEST_SIZE = 1024


def _templates(num_classes):
    rng = np.random.RandomState(100 + num_classes)
    return rng.uniform(0, 1, size=(num_classes, DIM)).astype("float32")


def _reader(n, num_classes, seed, cycle=False):
    def reader():
        tpl = _templates(num_classes)
        it = itertools.count() if cycle else range(n)
        rng = np.random.RandomState(seed)
        for _ in it:
            y = int(rng.randint(0, num_classes))
            x = tpl[y] + 0.25 * rng.randn(DIM).astype("float32")
            yield np.clip(x, 0, 1).astype("float32"), np.int64(y)

    return reader


def train100():
    return _reader(TRAIN_SIZE, 100, seed=11)


def test100():
    return _reader(TEST_SIZE, 100, seed=12)


def train10(cycle=False):
    return _reader(TRAIN_SIZE, 10, seed=13, cycle=cycle)


def test10(cycle=False):
    return _reader(TEST_SIZE, 10, seed=14, cycle=cycle)


def fetch():
    pass
