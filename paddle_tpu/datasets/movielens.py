"""Synthetic MovieLens-1M (python/paddle/dataset/movielens.py interface).
Samples follow the reference layout: [user_id, gender_id, age_id, job_id,
movie_id, category_ids(list), title_ids(list)] + [score]."""

import numpy as np

MAX_USER = 6040
MAX_MOVIE = 3952
MAX_JOB = 20
N_AGE = 7
N_CATEGORIES = 18
TITLE_VOCAB = 5174
TRAIN_SIZE = 4096
TEST_RATIO = 0.1

age_table = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index, [c for c in self.categories],
                [t for t in self.title]]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]


def _movie(mid):
    n_cat = 1 + mid % 3
    cats = [(mid * 7 + k) % N_CATEGORIES for k in range(n_cat)]
    title = [(mid * 13 + k) % TITLE_VOCAB for k in range(2 + mid % 4)]
    return MovieInfo(mid, cats, title)


def _user(uid):
    return UserInfo(uid, "M" if uid % 2 else "F",
                    age_table[uid % N_AGE], uid % (MAX_JOB + 1))


def movie_info():
    return {mid: _movie(mid) for mid in range(1, MAX_MOVIE + 1)}


def user_info():
    return {uid: _user(uid) for uid in range(1, MAX_USER + 1)}


def _reader(is_test):
    def reader():
        rng = np.random.RandomState(9 if is_test else 10)
        n = int(TRAIN_SIZE * TEST_RATIO) if is_test else TRAIN_SIZE
        for _ in range(n):
            uid = int(rng.randint(1, MAX_USER + 1))
            mid = int(rng.randint(1, MAX_MOVIE + 1))
            usr = _user(uid)
            mov = _movie(mid)
            # score correlates with (uid+mid) parity bands -> learnable
            score = float(1 + ((uid * 3 + mid * 5) % 5))
            yield usr.value() + mov.value() + [score]

    return reader


def train():
    return _reader(False)


def test():
    return _reader(True)


def get_movie_title_dict():
    return {("t%d" % i): i for i in range(TITLE_VOCAB)}


def max_movie_id():
    return MAX_MOVIE


def max_user_id():
    return MAX_USER


def max_job_id():
    return MAX_JOB


def movie_categories():
    return {("c%d" % i): i for i in range(N_CATEGORIES)}


def fetch():
    pass
