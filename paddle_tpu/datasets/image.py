"""Image preprocessing helpers (python/paddle/dataset/image.py interface)
implemented on numpy only (the reference shells out to cv2; zero-egress
environment has no cv2, and these ops are trivial in numpy).  Images are
HWC uint8/float arrays unless noted."""

import numpy as np

__all__ = [
    "load_image_bytes", "load_image", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform",
]


def load_image_bytes(bytes_data, is_color=True):
    """Decode a raw .npy byte payload (the synthetic stand-in for imdecode)."""
    import io

    arr = np.load(io.BytesIO(bytes_data), allow_pickle=False)
    return arr if is_color else arr.mean(axis=2)


def load_image(file, is_color=True):
    arr = np.load(file, allow_pickle=False)
    return arr if is_color else arr.mean(axis=2)


def _resize(im, h, w):
    """Nearest-neighbor resize (numpy index sampling)."""
    sh = (np.arange(h) * im.shape[0] / float(h)).astype(int)
    sw = (np.arange(w) * im.shape[1] / float(w)).astype(int)
    return im[sh][:, sw]


def resize_short(im, size):
    h, w = im.shape[:2]
    if h < w:
        return _resize(im, size, int(round(w * size / float(h))))
    return _resize(im, int(round(h * size / float(w))), size)


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = max((h - size) // 2, 0)
    w0 = max((w - size) // 2, 0)
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = np.random.randint(0, max(h - size, 0) + 1)
    w0 = np.random.randint(0, max(w - size, 0) + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1] if im.ndim == 2 else im[:, ::-1, :]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype("float32")
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, np.newaxis, np.newaxis]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
