"""Synthetic NLTK movie-reviews sentiment corpus
(python/paddle/dataset/sentiment.py interface: get_word_dict/train/test)."""

import numpy as np

VOCAB = 3000
TRAIN_SIZE = 1600
TEST_SIZE = 400
MIN_LEN, MAX_LEN = 10, 120


def get_word_dict():
    return [(("w%d" % i), i) for i in range(VOCAB)]


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        half = VOCAB // 2
        for _ in range(n):
            y = int(rng.randint(0, 2))
            ln = int(rng.randint(MIN_LEN, MAX_LEN + 1))
            lo, hi = (0, half + half // 3) if y else (half - half // 3, VOCAB)
            ids = rng.randint(lo, hi, size=ln).astype("int64")
            yield list(ids), np.int64(y)

    return reader


def train():
    return _reader(TRAIN_SIZE, 31)


def test():
    return _reader(TEST_SIZE, 32)


def fetch():
    pass
