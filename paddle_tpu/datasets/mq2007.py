"""Synthetic LETOR MQ2007 learning-to-rank
(python/paddle/dataset/mq2007.py interface: __reader__ with pointwise /
pairwise / listwise formats).  46-dim feature vectors whose first feature
correlates with relevance, so rankers can learn."""

import numpy as np

FEATURE_DIM = 46
N_QUERIES = 120
DOCS_PER_QUERY = (5, 20)


class Query:
    def __init__(self, query_id=-1, relevance_score=-1, feature_vector=None):
        self.query_id = query_id
        self.relevance_score = relevance_score
        self.feature_vector = feature_vector or []


class QueryList:
    def __init__(self, querylist=None):
        self.querylist = querylist or []

    def __iter__(self):
        return iter(self.querylist)

    def __len__(self):
        return len(self.querylist)

    def __getitem__(self, i):
        return self.querylist[i]

    def _correct_ranking_(self):
        self.querylist.sort(key=lambda q: -q.relevance_score)


def _queries(seed):
    rng = np.random.RandomState(seed)
    for qid in range(N_QUERIES):
        n = int(rng.randint(*DOCS_PER_QUERY))
        ql = QueryList()
        for _ in range(n):
            rel = int(rng.randint(0, 3))
            fv = rng.rand(FEATURE_DIM).astype("float64")
            fv[0] = rel / 2.0 + 0.1 * rng.randn()  # learnable signal
            ql.querylist.append(Query(qid, rel, list(fv)))
        yield ql


def gen_point(querylist):
    for q in querylist:
        yield q.relevance_score, np.array(q.feature_vector)


def gen_pair(querylist, partial_order="full"):
    querylist._correct_ranking_()
    for i, qi in enumerate(querylist):
        for qj in querylist[i + 1:]:
            if qi.relevance_score > qj.relevance_score:
                yield (1, np.array(qi.feature_vector),
                       np.array(qj.feature_vector))


def gen_list(querylist):
    querylist._correct_ranking_()
    labels = [q.relevance_score for q in querylist]
    features = [q.feature_vector for q in querylist]
    yield np.array(labels), np.array(features)


def __reader__(filepath=None, format="pairwise", shuffle=False,
               fill_missing=-1, seed=71):
    def reader():
        gen = {"pointwise": gen_point, "pairwise": gen_pair,
               "listwise": gen_list}[format]
        for ql in _queries(seed):
            for sample in gen(ql):
                yield sample

    return reader


def train(format="pairwise"):
    return __reader__(format=format, seed=71)


def test(format="pairwise"):
    return __reader__(format=format, seed=72)


def fetch():
    pass
