"""Synthetic PTB language-model corpus (python/paddle/dataset/imikolov.py
interface: build_dict/train/test, NGRAM and SEQ data types)."""

import numpy as np

VOCAB = 2074  # reference min_word_freq=50 dict size ballpark
TRAIN_SENTS = 2048
TEST_SENTS = 512
MIN_LEN, MAX_LEN = 4, 20


class DataType:
    NGRAM = 1
    SEQ = 2


def build_dict(min_word_freq=50):
    d = {("w%d" % i): i for i in range(VOCAB - 2)}
    d["<s>"] = VOCAB - 2
    d["<e>"] = VOCAB - 1
    return d


def _sentences(n, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        ln = int(rng.randint(MIN_LEN, MAX_LEN + 1))
        # markovian-ish: next word depends on previous (learnable bigrams)
        sent = [int(rng.randint(0, VOCAB - 2))]
        for _i in range(ln - 1):
            sent.append((sent[-1] * 31 + 7) % (VOCAB - 2))
        yield sent


def _reader(n, seed, word_idx, ngram_n, data_type):
    def reader():
        s_id, e_id = VOCAB - 2, VOCAB - 1
        for sent in _sentences(n, seed):
            ids = [s_id] + sent + [e_id]
            if data_type == DataType.NGRAM:
                if len(ids) >= ngram_n:
                    ids_np = np.asarray(ids, "int64")
                    for i in range(ngram_n - 1, len(ids_np)):
                        yield tuple(ids_np[i - ngram_n + 1:i + 1])
            else:
                yield np.asarray(ids[:-1], "int64"), np.asarray(
                    ids[1:], "int64")

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _reader(TRAIN_SENTS, 61, word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _reader(TEST_SENTS, 62, word_idx, n, data_type)


def fetch():
    pass
