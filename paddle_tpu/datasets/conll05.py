"""Synthetic CoNLL-2005 SRL (python/paddle/dataset/conll05.py interface:
test/get_dict/get_embedding).  Samples follow the reference's 9-slot
layout: (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_ids,
mark_ids, label_ids) with all sequences the same length."""

import numpy as np

WORD_DICT_LEN = 4000
LABEL_DICT_LEN = 59  # 29 BIO tags * 2 + O, reference label dict size
PRED_DICT_LEN = 300
EMB_DIM = 32
TEST_SIZE = 256
MIN_LEN, MAX_LEN = 5, 30


def get_dict():
    word_dict = {("w%d" % i): i for i in range(WORD_DICT_LEN)}
    verb_dict = {("v%d" % i): i for i in range(PRED_DICT_LEN)}
    label_dict = {("L%d" % i): i for i in range(LABEL_DICT_LEN)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    rng = np.random.RandomState(55)
    return rng.uniform(-1, 1, (WORD_DICT_LEN, EMB_DIM)).astype("float32")


def test():
    def reader():
        rng = np.random.RandomState(56)
        for _ in range(TEST_SIZE):
            ln = int(rng.randint(MIN_LEN, MAX_LEN + 1))
            words = rng.randint(0, WORD_DICT_LEN, ln).astype("int64")
            ctx = [np.roll(words, k) for k in (2, 1, 0, -1, -2)]
            verb_pos = int(rng.randint(0, ln))
            verb = np.full(ln, int(words[verb_pos]) % PRED_DICT_LEN, "int64")
            mark = np.zeros(ln, "int64")
            mark[verb_pos] = 1
            # labels correlate with word ids so models can learn
            labels = (words + verb[0]) % LABEL_DICT_LEN
            yield tuple(list(w) for w in
                        [words] + ctx + [verb, mark, labels.astype("int64")])

    return reader


def fetch():
    pass
