"""Synthetic MNIST (python/paddle/dataset/mnist.py interface).

Deterministic learnable digits: each class has a fixed random template;
samples are the template plus noise.  Readers yield (image[784] float32 in
[-1, 1], label int64) like the reference.
"""

import numpy as np

TRAIN_SIZE = 8192
TEST_SIZE = 1024
IMAGE_SIZE = 784
NUM_CLASSES = 10


def _templates():
    rng = np.random.RandomState(42)
    return rng.uniform(-1, 1, size=(NUM_CLASSES, IMAGE_SIZE)).astype("float32")


def _reader(n, seed):
    def reader():
        tpl = _templates()
        rng = np.random.RandomState(seed)
        labels = rng.randint(0, NUM_CLASSES, size=n)
        for i in range(n):
            y = int(labels[i])
            x = tpl[y] + 0.35 * rng.randn(IMAGE_SIZE).astype("float32")
            yield np.clip(x, -1, 1).astype("float32"), np.int64(y)

    return reader


def train():
    return _reader(TRAIN_SIZE, seed=1)


def test():
    return _reader(TEST_SIZE, seed=2)
