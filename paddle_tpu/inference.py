"""Inference engine: AnalysisConfig + AnalysisPredictor
(parity: paddle/fluid/inference/api/analysis_predictor.h:47,
paddle_analysis_config.h, paddle_api.h PaddleTensor/ZeroCopyTensor).

The reference's analysis pipeline (ir passes, TensorRT subgraphs, params
sync) collapses on TPU into: prune to the feed→fetch slice (already done by
save_inference_model), hold params in a private Scope, and let the
block-compiling Executor stage the whole program into ONE cached XLA
executable — the "engine op" is the entire program.  Clones share the scope
(reference: AnalysisPredictor::Clone shares params the same way).
"""

import numpy as np

from . import io as _io
from .core.executor import Executor, scope_guard
from .core.scope import Scope
from .framework import CPUPlace, TPUPlace

__all__ = [
    "AnalysisConfig", "PaddleTensor", "ZeroCopyTensor",
    "create_paddle_predictor", "AnalysisPredictor",
]


def _enable_executable_cache(path):
    """Route compiled executables through the unified two-tier cache
    (core/compile_cache.py): tier A is XLA's persistent cache wired by
    enable_xla_cache(), tier B holds whole-step AOT artifacts — the same
    store Executor.warmup and the elastic standby path use, so a serving
    replica restores the buckets a trainer or earlier replica compiled."""
    from . import flags as _flags
    from .core import compile_cache as _cc

    path = str(path)
    if _flags.flag("compile_cache_dir") != path:
        _flags.set_flags({"FLAGS_compile_cache_dir": path})
    _cc.enable_xla_cache()


class AnalysisConfig:
    """Mirror of paddle_analysis_config.h's commonly-used surface."""

    def __init__(self, model_dir_or_prog_file=None, params_file=None):
        # reference ctor forms (paddle_analysis_config.h): one arg = model
        # dir; two args = (prog_file, params_file)
        if params_file is None:
            self._model_dir = model_dir_or_prog_file
            self._prog_file = None
            self._params_file = None
        else:
            self._model_dir = None
            self._prog_file = model_dir_or_prog_file
            self._params_file = params_file
        self._use_tpu = True
        self._device_id = 0
        self._ir_optim = True
        self._memory_optim = True
        self._feed_fetch_ops = False
        self._cpu_math_threads = 1

    # -- model location ------------------------------------------------------
    def set_model(self, a, b=None):
        if b is None:
            self._model_dir = a
        else:
            self._prog_file, self._params_file = a, b

    def model_dir(self):
        return self._model_dir

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    # -- device --------------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # GPU request maps to the TPU chip (the reference's CUDAPlace slot)
        self._use_tpu = True
        self._device_id = device_id

    def enable_use_tpu(self, device_id=0):
        self._use_tpu = True
        self._device_id = device_id

    def disable_gpu(self):
        self._use_tpu = False

    def use_gpu(self):
        return self._use_tpu

    def gpu_device_id(self):
        return self._device_id

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = n

    # -- optimization toggles (XLA owns these; kept for API parity) ---------
    # -- serialized executable cache ----------------------------------------
    def set_optim_cache_dir(self, path):
        """Persist compiled executables across processes (the reference's
        TensorRT SetOptimCacheDir serialized-engine cache,
        paddle_analysis_config.h): compiled XLA executables are serialized
        into `path` and re-loaded by later predictors/processes, skipping
        compilation."""
        self._optim_cache_dir = path

    def optim_cache_dir(self):
        return getattr(self, "_optim_cache_dir", None)

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self):
        self._memory_optim = True

    def enable_mkldnn(self):
        pass

    def switch_use_feed_fetch_ops(self, flag=True):
        self._feed_fetch_ops = flag

    def switch_specify_input_names(self, flag=True):
        pass

    def tensorrt_engine_enabled(self):
        return False


class PaddleTensor:
    """Simple named ndarray container (paddle_api.h PaddleTensor)."""

    def __init__(self, data=None, name=""):
        self.name = name
        self.data = np.asarray(data) if data is not None else None
        self.shape = tuple(self.data.shape) if data is not None else ()
        self.lod = []

    def as_ndarray(self):
        return self.data


class ZeroCopyTensor:
    """Handle onto one feed/fetch slot of a predictor
    (paddle_api.h ZeroCopyTensor): copy_from_cpu stages the next input,
    copy_to_cpu reads the last output."""

    def __init__(self, predictor, name, is_input):
        self._pred = predictor
        self._name = name
        self._is_input = is_input

    def name(self):
        return self._name

    def copy_from_cpu(self, arr):
        if not self._is_input:
            raise RuntimeError("copy_from_cpu on an output tensor")
        self._pred._staged_feed[self._name] = np.asarray(arr)

    def reshape(self, shape):
        pass  # shape comes from the staged array

    def copy_to_cpu(self):
        if self._is_input:
            raise RuntimeError("copy_to_cpu on an input tensor")
        out = self._pred._last_outputs
        if out is None:
            raise RuntimeError("run the predictor before copy_to_cpu")
        return out[self._name]


class AnalysisPredictor:
    def __init__(self, config, _shared=None):
        self._config = config
        if config.optim_cache_dir():
            _enable_executable_cache(config.optim_cache_dir())
        if _shared is not None:
            # clone: share program + scope (shared params, reference
            # AnalysisPredictor::Clone) AND the Executor — its executable
            # cache is per-instance, so a private Executor would recompile
            # per clone; sharing it means N threaded clones hit ONE
            # compiled executable (Executor.run is thread-safe for
            # inference programs: the compiled fn is pure, params read from
            # the shared scope)
            (self._program, self._feed_names, self._fetch_vars, self._scope,
             self._exe) = _shared
        else:
            place = TPUPlace(config.gpu_device_id()) if config.use_gpu() \
                else CPUPlace()
            self._exe = Executor(place)
            import os

            self._scope = Scope()
            dirname = config.model_dir()
            model_filename = params_filename = None
            if dirname is None:
                # two-file form: both files must live in one directory (the
                # save_inference_model layout)
                prog, params = config.prog_file(), config.params_file()
                if not prog:
                    raise ValueError(
                        "AnalysisConfig: set_model(dir) or "
                        "set_model(prog_file, params_file) is required")
                dirname = os.path.dirname(prog) or "."
                if (os.path.dirname(params) or ".") != dirname:
                    raise ValueError(
                        "prog_file and params_file must be in the same "
                        "directory (got %r / %r)" % (prog, params))
                model_filename = os.path.basename(prog)
                params_filename = os.path.basename(params)
            with scope_guard(self._scope):
                self._program, self._feed_names, self._fetch_vars = \
                    _io.load_inference_model(dirname, self._exe,
                                             model_filename, params_filename)
            if config.ir_optim():
                # analysis pass pipeline (analysis_predictor.cc:461
                # OptimizeInferenceProgram): graph-rewriting passes whose
                # wins XLA can't recover (they rewrite parameter values /
                # delete stateful ops); everything else is XLA's job
                from . import ir as _ir

                # fetch targets have no op consumers after load (feed/fetch
                # ops are stripped) — protect them from fusion swallowing
                protected = set(self._feed_names) | {
                    v.name for v in self._fetch_vars}
                for pname in ("delete_dropout_pass", "conv_bn_fuse_pass",
                              "multihead_matmul_fuse_pass",
                              "fc_fuse_pass", "repeated_fc_relu_fuse_pass",
                              "seqpool_concat_fuse_pass",
                              "fuse_elewise_add_act_pass"):
                    _ir.apply_pass(pname, self._program, self._scope,
                                   protected=protected)
        self._fetch_names = [v.name for v in self._fetch_vars]
        self._staged_feed = {}
        self._last_outputs = None

    # -- PaddleTensor API ----------------------------------------------------
    def run(self, inputs):
        """inputs: list[PaddleTensor] in get_input_names() order (or named).
        Returns list[PaddleTensor]."""
        feed = {}
        for i, t in enumerate(inputs):
            name = t.name or self._feed_names[i]
            feed[name] = t.data
        outs = self._run_feed(feed)
        return [PaddleTensor(outs[n], name=n) for n in self._fetch_names]

    # -- ZeroCopy API --------------------------------------------------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def get_input_tensor(self, name):
        if name not in self._feed_names:
            raise KeyError(name)
        return ZeroCopyTensor(self, name, True)

    def get_output_tensor(self, name):
        if name not in self._fetch_names:
            raise KeyError(name)
        return ZeroCopyTensor(self, name, False)

    def zero_copy_run(self):
        missing = [n for n in self._feed_names if n not in self._staged_feed]
        if missing:
            raise RuntimeError("inputs not staged: %s" % missing)
        self._last_outputs = self._run_feed(dict(self._staged_feed))

    # -- internals -----------------------------------------------------------
    def _run_feed(self, feed):
        with scope_guard(self._scope):
            vals = self._exe.run(self._program, feed=feed,
                                 fetch_list=self._fetch_vars)
        return dict(zip(self._fetch_names, [np.asarray(v) for v in vals]))

    def clone(self):
        return AnalysisPredictor(
            self._config,
            _shared=(self._program, self._feed_names, self._fetch_vars,
                     self._scope, self._exe))

    def program(self):
        return self._program


def create_paddle_predictor(config):
    """Factory (paddle_api.h CreatePaddlePredictor)."""
    return AnalysisPredictor(config)
