"""ParallelExecutor facade (reference
python/paddle/fluid/parallel_executor.py:28): the legacy multi-device API.
On TPU it wraps CompiledProgram.with_data_parallel over the mesh — the SSA
op-handle engine dissolves into SPMD (COMPONENTS.md §2.1)."""

from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from .core.executor import Executor, global_scope
from .framework import CPUPlace, TPUPlace, default_main_program

__all__ = ["ParallelExecutor"]


class ParallelExecutor:
    def __init__(self, use_cuda, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        self._place = TPUPlace(0) if use_cuda else CPUPlace()
        self._main = main_program or default_main_program()
        self._scope = scope or global_scope()
        self._compiled = CompiledProgram(self._main).with_data_parallel(
            loss_name=loss_name, build_strategy=build_strategy,
            exec_strategy=exec_strategy,
            share_vars_from=getattr(share_vars_from, "_compiled",
                                    share_vars_from))
        self._exe = Executor(self._place)

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        feed = feed if feed is not None else feed_dict
        from .core.executor import scope_guard

        with scope_guard(self._scope):
            return self._exe.run(self._compiled, feed=feed,
                                 fetch_list=fetch_list,
                                 return_numpy=return_numpy)

    def drop_local_exe_scopes(self):
        pass  # scope lifetime is owned by XLA/PJRT buffers here
