"""Python metric accumulators (parity: python/paddle/fluid/metrics.py).

Numpy-side running accumulators updated from fetched batch outputs — the
same contract as the reference (update() with numpy arrays, eval() returns
the aggregate, reset() clears state).
"""

import numpy as np

__all__ = [
    "MetricBase", "CompositeMetric", "Precision", "Recall", "Accuracy",
    "ChunkEvaluator", "EditDistance", "Auc", "DetectionMAP",
]


def _to_np(x):
    return np.asarray(x)


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    def get_config(self):
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_")}


class CompositeMetric(MetricBase):
    """Holds several metrics updated with the same inputs
    (metrics.py CompositeMetric)."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise ValueError("add_metric expects a MetricBase")
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary precision over {0,1} predictions (metrics.py Precision)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(_to_np(preds)).astype(np.int64).ravel()
        labels = _to_np(labels).astype(np.int64).ravel()
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(_to_np(preds)).astype(np.int64).ravel()
        labels = _to_np(labels).astype(np.int64).ravel()
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        rel = self.tp + self.fn
        return float(self.tp) / rel if rel else 0.0


class Accuracy(MetricBase):
    """Weighted running accuracy (metrics.py Accuracy): update(value,
    weight) with the batch accuracy value (e.g. from layers.accuracy)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        if weight < 0:
            raise ValueError("weight must be >= 0")
        self.value += float(np.asarray(value).ravel()[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy has no accumulated data")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """F1 over chunk counts (metrics.py ChunkEvaluator): update with
    (num_infer_chunks, num_label_chunks, num_correct_chunks)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        def scalar(x):
            return int(np.asarray(x).ravel()[0])

        self.num_infer_chunks += scalar(num_infer_chunks)
        self.num_label_chunks += scalar(num_label_chunks)
        self.num_correct_chunks += scalar(num_correct_chunks)

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    """Average edit distance + instance error rate (metrics.py
    EditDistance): update with per-instance distances and an error count."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = _to_np(distances).ravel()
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(distances != 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance has no accumulated data")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class Auc(MetricBase):
    """ROC AUC via fixed thresholds histogram (metrics.py Auc)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        n = self._num_thresholds + 1
        self._stat_pos = np.zeros(n, dtype=np.int64)
        self._stat_neg = np.zeros(n, dtype=np.int64)

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels).ravel().astype(np.int64)
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.ravel()
        bins = np.minimum(
            (pos_prob * self._num_thresholds).astype(np.int64),
            self._num_thresholds)
        np.add.at(self._stat_pos, bins[labels == 1], 1)
        np.add.at(self._stat_neg, bins[labels != 1], 1)

    @staticmethod
    def trapezoid_area(x1, x2, y1, y2):
        return abs(x1 - x2) * (y1 + y2) / 2.0

    def eval(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        idx = self._num_thresholds
        while idx >= 0:
            new_pos = tot_pos + self._stat_pos[idx]
            new_neg = tot_neg + self._stat_neg[idx]
            auc += self.trapezoid_area(tot_neg, new_neg, tot_pos, new_pos)
            tot_pos, tot_neg = new_pos, new_neg
            idx -= 1
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0


class DetectionMAP(MetricBase):
    """Mean average precision accumulator for detection
    (metrics.py DetectionMAP, simplified: accumulates per-batch mAP values
    computed by the detection_map op and averages them)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.has_state = False
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        self.value += float(np.asarray(value).ravel()[0]) * weight
        self.weight += weight
        self.has_state = True

    def eval(self):
        if not self.has_state:
            raise ValueError("DetectionMAP has no accumulated data")
        return self.value / self.weight
