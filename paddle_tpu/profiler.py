"""Profiler: host event recording + chrome-trace export + device tracing.

TPU-native analog of the reference's profiler stack
(paddle/fluid/platform/profiler.h:81 RecordEvent, :166 Enable/DisableProfiler;
python/paddle/fluid/profiler.py:228 profiler context manager; CUPTI device
tracing in platform/device_tracer.h; tools/timeline.py chrome-trace export).

Host events come from RAII `RecordEvent` scopes placed on the executor and
dygraph hot paths (zero-cost when disabled, mirroring the
`IsProfileEnabled()` guard at operator.cc:162-171).  Device-side profiling
delegates to jax.profiler (XPlane/TensorBoard) — the TPU replacement for
CUPTI.  `save_chrome_trace` writes the host timeline in the same
chrome://tracing JSON format timeline.py produced.
"""

import contextlib
import json
import threading
import time

__all__ = [
    "RecordEvent", "profiler", "start_profiler", "stop_profiler",
    "reset_profiler", "save_chrome_trace", "cuda_profiler", "mark_instant",
]

_enabled = False
_events = []  # (name, tid, start_us, dur_us)
_instants = []  # (name, tid, ts_us, args) — ph:"i" step markers
_lock = threading.Lock()
_device_trace_dir = None


def is_profiler_enabled():
    return _enabled


class RecordEvent:
    """RAII host event (platform/profiler.h:81).  Usable as a context
    manager or via push/pop."""

    __slots__ = ("name", "_t0")

    def __init__(self, name):
        self.name = name
        self._t0 = None

    def __enter__(self):
        if _enabled:
            self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            dur = (time.perf_counter_ns() - self._t0) // 1000
            with _lock:
                _events.append((self.name, threading.get_ident(),
                                self._t0 // 1000, dur))
        return False


def mark_instant(name, args=None):
    """Record an instant marker (chrome-trace ph:"i", e.g. the executor's
    per-step boundary) so host spans, step edges, and XPlane device
    timelines line up in Perfetto.  Zero-cost when the profiler is off."""
    if not _enabled:
        return
    with _lock:
        _instants.append((name, threading.get_ident(),
                          time.perf_counter_ns() // 1000, args))


def reset_profiler():
    with _lock:
        _events.clear()
        _instants.clear()


def start_profiler(state="All", tracer_option=None, device_trace_dir=None):
    """state: CPU | GPU | All (kept for API parity; host events always on,
    device tracing via jax.profiler when device_trace_dir is given)."""
    global _enabled, _device_trace_dir
    _enabled = True
    if device_trace_dir:
        import jax

        jax.profiler.start_trace(device_trace_dir)
        _device_trace_dir = device_trace_dir


def stop_profiler(sorted_key=None, profile_path=None):
    """Print the event summary (reference profiler's table) and optionally
    dump the chrome trace to `profile_path`."""
    global _enabled, _device_trace_dir
    _enabled = False
    if _device_trace_dir:
        import jax

        try:
            jax.profiler.stop_trace()
        finally:
            # a failed device-trace stop must still clear the global:
            # leaving it dangling would make the NEXT start_profiler pair a
            # fresh start_trace with a stop for the dead session
            _device_trace_dir = None
    if profile_path:
        save_chrome_trace(profile_path)
    _print_summary(sorted_key)


def _print_summary(sorted_key=None):
    with _lock:
        events = list(_events)
    agg = {}
    for name, _, _, dur in events:
        tot, cnt, mn, mx = agg.get(name, (0, 0, float("inf"), 0))
        agg[name] = (tot + dur, cnt + 1, min(mn, dur), max(mx, dur))
    rows = [
        (name, cnt, tot / 1e3, (tot / cnt) / 1e3, mn / 1e3, mx / 1e3)
        for name, (tot, cnt, mn, mx) in agg.items()
    ]
    keyfns = {
        None: lambda r: -r[2], "default": lambda r: -r[2],
        "calls": lambda r: -r[1], "total": lambda r: -r[2],
        "ave": lambda r: -r[3], "min": lambda r: r[4], "max": lambda r: -r[5],
    }
    if sorted_key not in keyfns:
        raise ValueError(
            "sorted_key must be one of %s, got %r"
            % (sorted(k for k in keyfns if k), sorted_key))
    rows.sort(key=keyfns[sorted_key])
    if not rows:
        print("profiler: no events recorded")
        return
    print("%-40s %8s %12s %10s %10s %10s"
          % ("Event", "Calls", "Total(ms)", "Ave(ms)", "Min(ms)", "Max(ms)"))
    for r in rows:
        print("%-40s %8d %12.3f %10.3f %10.3f %10.3f" % r)


def save_chrome_trace(path):
    """chrome://tracing JSON (tools/timeline.py:131 analog).

    Besides the ph:"X" host spans, the trace carries ph:"M" process/thread
    name metadata (labeled tracks instead of bare tids in Perfetto) and the
    ph:"i" per-step instant markers recorded by mark_instant, so step
    boundaries line up against both host spans and XPlane device lanes."""
    with _lock:
        events = list(_events)
        instants = list(_instants)
    tids = sorted({tid for _, tid, _, _ in events}
                  | {tid for _, tid, _, _ in instants})
    trace_events = [
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": "paddle_tpu host"}},
        {"name": "process_sort_index", "ph": "M", "pid": 0,
         "args": {"sort_index": 0}},
    ]
    for i, tid in enumerate(tids):
        trace_events.append(
            {"name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
             "args": {"name": "host thread %d" % i
                      if i else "host main"}})
    trace_events += [
        {"name": name, "ph": "X", "pid": 0, "tid": tid,
         "ts": ts, "dur": dur, "cat": "host"}
        for name, tid, ts, dur in events
    ]
    trace_events += [
        {"name": name, "ph": "i", "s": "g", "pid": 0, "tid": tid,
         "ts": ts, "cat": "step", "args": args or {}}
        for name, tid, ts, args in instants
    ]
    trace = {"traceEvents": trace_events}
    with open(path, "w") as f:
        json.dump(trace, f)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None,
             tracer_option=None, device_trace_dir=None):
    """`with fluid.profiler.profiler('All', 'total', '/tmp/profile.json'):`"""
    if sorted_key not in (None, "default", "calls", "total", "ave", "min",
                          "max"):
        # fail before running the profiled body, not from the finally block
        raise ValueError("invalid sorted_key %r" % (sorted_key,))
    start_profiler(state, tracer_option, device_trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    """API-parity shim (profiler.py cuda_profiler): no CUDA on TPU builds;
    behaves as the generic profiler."""
    start_profiler()
    try:
        yield
    finally:
        stop_profiler()
