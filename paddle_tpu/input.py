"""Top-level input helpers (reference python/paddle/fluid/input.py:
one_hot:24, embedding:126).  These are the *_v2 semantics: ids carry NO
mandatory trailing [1] dim, and the output appends the new axis to the ids
shape (unlike layers.one_hot / layers.embedding, whose v1 ops squeeze a
trailing [1])."""

from .layer_helper import LayerHelper

__all__ = ["one_hot", "embedding"]


def one_hot(input, depth, allow_out_of_range=False):
    """fluid.one_hot (input.py:24): out shape = ids.shape + [depth]."""
    from . import layers

    flat = layers.reshape(input, shape=[-1, 1])
    oh = layers.one_hot(flat, depth, allow_out_of_range=allow_out_of_range)
    out_shape = [d if d > 0 else -1 for d in (input.shape or [-1])]
    return layers.reshape(oh, shape=out_shape + [depth])


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """fluid.embedding (input.py:126): lookup_table_v2 — out shape =
    ids.shape + [emb_dim]."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(attr=param_attr, shape=size, dtype=dtype,
                                is_bias=False)
    out = helper.create_variable_for_type_inference(dtype)
    pad = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op(
        type="lookup_table_v2",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [out]},
        attrs={"is_sparse": is_sparse, "is_distributed": is_distributed,
               "padding_idx": pad})
    return out
