"""Autodiff by program rewriting.

TPU-native port of ``python/paddle/fluid/backward.py`` (append_backward at
:933): walk the block's ops in reverse, emit each op's grad ops (from the
registry's grad makers — hand-written or the vjp-based default), accumulate
duplicate gradients with `sum` ops (_addup_repetitive_outputs_ analog), and
annotate ops with op_role/op_role_var so optimizers and the distributed
transpilers can find {param, grad} pairs.
"""

from .core.registry import get_op_def
from .framework import (
    GRAD_SUFFIX,
    OP_ROLE_KEY,
    OP_ROLE_VAR_KEY,
    OpRole,
    Parameter,
    _grad_var_name,
)

__all__ = ["append_backward", "gradients", "calc_gradient"]


def _collect_no_grad(block, no_grad_set):
    ng = set(no_grad_set or ())
    for name, var in block.vars.items():
        if var.stop_gradient:
            ng.add(name)
    return _propagate_no_grad(block, ng)


def _propagate_no_grad(block, ng):
    """Forward-close the no-grad set (reference _find_no_grad_vars /
    _find_op_path_ pruning, backward.py:1090): a var computed ONLY from
    no-grad inputs — or by an op with no gradient maker, or with no inputs
    at all (constants, random fills) — can never receive a gradient, so
    the backward pass must not build grad chains below it.  Without this,
    attention-mask plumbing (cast/scale/matmul of stop-gradient data)
    left whole chains of dead sum/reshape_grad/scale_grad ops in every
    BERT and transformer program."""
    for op in block.ops:
        if op.attr(OP_ROLE_KEY) == OpRole.Optimize:
            continue
        try:
            opdef = get_op_def(op.type)
        except ValueError:
            continue
        outs = [n for n in op.output_arg_names if n]
        if not outs:
            continue
        if opdef.grad_maker is None:
            dead = True
        else:
            ins = [n for slot in opdef.input_slots
                   if slot not in opdef.no_grad_inputs
                   for n in op.input(slot) if n]
            dead = all(n in ng for n in ins)  # vacuous for zero-input ops
        if dead:
            # never absorb an in-place alias of a differentiable var (a
            # counter/accumulator written over itself stays as-is)
            ng.update(n for n in outs if n not in op.input_arg_names)
    return ng


def _relevant_ops(block, loss_name, no_grad_set, stop_at=None):
    """Reverse-reachability: ops whose outputs (transitively) feed the loss.
    Returns (op_index_list_in_reverse, grad_flow_names)."""
    grad_flow = {loss_name}
    relevant = []
    for idx in range(len(block.ops) - 1, -1, -1):
        op = block.ops[idx]
        if op.attr(OP_ROLE_KEY) == OpRole.Optimize:
            continue
        outs = [n for n in op.output_arg_names if n]
        if not any(n in grad_flow for n in outs):
            continue
        opdef = get_op_def(op.type)
        if opdef.grad_maker is None:
            continue
        relevant.append(idx)
        for slot in opdef.input_slots:
            if slot in opdef.no_grad_inputs:
                continue
            for n in op.input(slot):
                if n and n not in no_grad_set:
                    if stop_at is not None and n in stop_at:
                        continue
                    grad_flow.add(n)
    return relevant, grad_flow


def _dedup_grad_ops(grad_op_descs):
    """Rename duplicate grad outputs and insert sum ops
    (_addup_repetitive_outputs_ analog, reference backward.py:167)."""
    producers = {}
    for gop in grad_op_descs:
        for slot, names in gop.outputs.items():
            for n in names:
                if n:
                    producers[n] = producers.get(n, 0) + 1
    multi = {n for n, c in producers.items() if c > 1}
    if not multi:
        return grad_op_descs

    result = []
    seen = {n: 0 for n in multi}
    renames = {n: [] for n in multi}
    remaining = {n: producers[n] for n in multi}
    from .core.registry import GradOpDesc

    for gop in grad_op_descs:
        finished = []
        for slot, names in list(gop.outputs.items()):
            new_names = []
            for n in names:
                if n in multi:
                    i = seen[n]
                    seen[n] += 1
                    rn = "%s@RENAME@%d" % (n, i)
                    renames[n].append(rn)
                    remaining[n] -= 1
                    if remaining[n] == 0:
                        finished.append(n)
                    new_names.append(rn)
                else:
                    new_names.append(n)
            gop.outputs[slot] = new_names
        result.append(gop)
        for n in finished:
            result.append(
                GradOpDesc(
                    "sum",
                    {"X": list(renames[n])},
                    {"Out": [n]},
                    {OP_ROLE_KEY: OpRole.Backward},
                )
            )
    return result


def _append_grad_op(block, gop, grad_to_var):
    """Materialize a GradOpDesc: create missing grad vars then append."""
    for slot, names in gop.outputs.items():
        for n in names:
            if not n or block.has_var_recursive(n):
                continue
            base = n.split("@RENAME@")[0]
            src = None
            if base.endswith(GRAD_SUFFIX):
                src = block._find_var_recursive(base[: -len(GRAD_SUFFIX)])
            if src is not None:
                block.create_var(name=n, shape=src.shape, dtype=src.dtype)
            else:
                block.create_var(name=n)
    attrs = dict(gop.attrs)
    attrs[OP_ROLE_KEY] = OpRole.Backward
    return block.append_op(
        type=gop.type, inputs=gop.inputs, outputs=gop.outputs, attrs=attrs
    )


class _RematPlan:
    """Segment-recompute plan for activation checkpointing (reference
    `_append_backward_ops_with_checkpoints_`, backward.py:576).

    `checkpoints` are variable names that stay materialized.  Ops between
    consecutive checkpoints form a segment; each segment's interior forward
    ops are replayed in the backward region (cloned with `@RECOMPUTE`
    output names, reading segment-boundary values through a
    `remat_barrier` so XLA CSE cannot merge the replay with the original
    forward), and the segment's grad ops read the recomputed values.  RNG /
    stateful ops (dropout) are never replayed — their outputs count as
    saved, so the replay reuses the original mask and stays bit-identical.
    """

    def __init__(self, block, relevant, checkpoints):
        from .core.registry import get_op_def as _get

        self.block = block
        ckpt = {c.name if hasattr(c, "name") else c for c in checkpoints}
        fwd_idx = sorted(relevant)
        # segment id per op: split AFTER an op that produces a checkpoint
        self._seg_of = {}
        seg = 0
        for i in fwd_idx:
            self._seg_of[i] = seg
            outs = set(block.ops[i].output_arg_names)
            if outs & ckpt:
                seg += 1
        n_seg = seg + 1
        # the tail segment (after the last checkpoint, the loss head) is
        # not replayed: its grads run first, its activations die young
        self._tail = n_seg - 1
        self._ops_in = {}
        for i in fwd_idx:
            self._ops_in.setdefault(self._seg_of[i], []).append(i)
        self._saved = ckpt
        self._clone_map = {}   # seg -> {inner name -> replay name}
        self._boundary = {}    # seg -> [external input names]
        for s, idxs in self._ops_in.items():
            if s == self._tail:
                continue
            inner, produced = {}, set()
            boundary = []
            for i in idxs:
                op = block.ops[i]
                opdef = _get(op.type)
                uses_rng = opdef.n_rng > 0 and (
                    opdef.rng_when is None or opdef.rng_when(op.attrs))
                replayable = not (opdef.stateful or uses_rng)
                for n in op.input_arg_names:
                    if not n or n in produced or n in inner:
                        continue
                    v = block._find_var_recursive(n)
                    if n not in boundary and (
                            v is None or not v.persistable):
                        boundary.append(n)
                for n in op.output_arg_names:
                    if not n:
                        continue
                    produced.add(n)
                    if replayable and n not in ckpt:
                        inner[n] = n + "@RECOMPUTE"
            self._clone_map[s] = inner
            self._boundary[s] = boundary

    def segment_of(self, idx):
        s = self._seg_of.get(idx)
        if s is None or s == self._tail:
            return None
        return s

    def clone_descs(self, seg):
        """remat_barrier + forward replay clones for one segment, in
        forward order."""
        from .core.registry import GradOpDesc, get_op_def as _get
        from .framework import OP_ROLE_KEY, OpRole

        cmap = self._clone_map[seg]
        if not cmap:
            return []
        boundary = self._boundary[seg]
        bar = {n: "%s@RMTBAR%d" % (n, seg) for n in boundary}
        descs = []
        if boundary:
            descs.append(GradOpDesc(
                "remat_barrier",
                {"X": list(boundary)},
                {"Out": [bar[n] for n in boundary]},
                {OP_ROLE_KEY: OpRole.Backward},
            ))

        def rd(n):
            return cmap.get(n, bar.get(n, n))

        for i in self._ops_in[seg]:
            op = self.block.ops[i]
            opdef = _get(op.type)
            if opdef.stateful or opdef.n_rng > 0:
                continue  # outputs treated as saved
            if not any(n in cmap for names in op.outputs.values()
                       for n in names):
                continue  # op only produces checkpoints/saved values
            # non-inner outputs (checkpoints, running-stat state) must not
            # be overwritten by the replay: route them to dead names
            outs = {slot: [cmap.get(n, n + "@RMTDEAD") if n else n
                           for n in names]
                    for slot, names in op.outputs.items()}
            descs.append(GradOpDesc(
                op.type,
                {slot: [rd(n) if n else n for n in names]
                 for slot, names in op.inputs.items()},
                outs,
                dict(op.attrs),
            ))
        return descs

    def remap_gop(self, seg, gop):
        """Point a grad op's forward-value inputs at the replayed names.
        GRAD@* slots carry gradients (original naming chain) — untouched."""
        cmap = self._clone_map[seg]
        for slot, names in list(gop.inputs.items()):
            if slot.startswith("GRAD@"):
                continue
            gop.inputs[slot] = [cmap.get(n, n) if n else n for n in names]


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Append grad ops for `loss` to its program; return [(param, grad)].

    Reference: backward.py:933.  `checkpoints` triggers recompute-friendly
    ordering (the vjp-based grads already recompute forward locally; XLA CSE
    or jax.checkpoint policies control materialization).
    """
    program = loss.block.program
    block = program.global_block()
    no_grad = _collect_no_grad(block, no_grad_set)
    if checkpoints is None:
        checkpoints = getattr(program, "_recompute_checkpoints", None)

    with program._backward_role_guard():
        # d(loss)/d(loss) = 1
        loss_grad_name = _grad_var_name(loss.name)
        block.create_var(name=loss_grad_name, shape=loss.shape or (1,),
                         dtype=loss.dtype)
        from .ops.common import dtype_enum

        block.append_op(
            type="fill_constant",
            outputs={"Out": [loss_grad_name]},
            attrs={
                "shape": list(loss.shape or (1,)),
                "value": 1.0,
                "dtype": dtype_enum(loss.dtype or "float32"),
                OP_ROLE_KEY: OpRole.Backward | OpRole.Loss,
            },
        )

        relevant, grad_flow = _relevant_ops(block, loss.name, no_grad)

        remat = _RematPlan(block, relevant, checkpoints) if checkpoints \
            else None

        grad_op_descs = []
        emitted_segments = set()
        for idx in relevant:
            op = block.ops[idx]
            opdef = get_op_def(op.type)
            ng = no_grad | {n for n in op.input_arg_names
                            if n and n not in grad_flow}
            gops = opdef.make_grad_ops(op, ng)
            if remat is not None:
                seg = remat.segment_of(idx)
                if seg is not None and seg not in emitted_segments:
                    # first grad op of this segment (reverse order): emit
                    # the barrier + forward replay clones ahead of it
                    emitted_segments.add(seg)
                    grad_op_descs.extend(remat.clone_descs(seg))
                if seg is not None:
                    for gop in gops:
                        remat.remap_gop(seg, gop)
            grad_op_descs.extend(gops)

        grad_op_descs = _dedup_grad_ops(grad_op_descs)

        grad_to_var = {}
        for gop in grad_op_descs:
            _append_grad_op(block, gop, grad_to_var)

    # collect (param, grad) pairs
    if parameter_list is not None:
        params = []
        for p in parameter_list:
            params.append(block.var(p) if isinstance(p, str) else p)
    else:
        params = [p for p in block.all_parameters() if p.trainable]

    params_and_grads = []
    for p in params:
        gname = _grad_var_name(p.name)
        if not block.has_var_recursive(gname):
            continue
        g = block.var(gname)
        if g.shape is None or g.shape != p.shape:
            g.shape = p.shape
        if g.dtype is None:
            g.dtype = p.dtype
        params_and_grads.append((p, g))

    # annotate op_role_var on the grad-producing ops (collective transpiler
    # keys off this to insert c_allreduce between backward and optimize,
    # reference transpiler/collective.py:208)
    grad_names = {g.name: p.name for p, g in params_and_grads}
    for op in block.ops:
        if op.attr(OP_ROLE_KEY) is None or not (
            int(op.attr(OP_ROLE_KEY)) & OpRole.Backward
        ):
            continue
        rv = list(op.attrs.get(OP_ROLE_VAR_KEY, []))
        for n in op.output_arg_names:
            if n in grad_names:
                rv.extend([grad_names[n], n])
        if rv:
            op.attrs[OP_ROLE_VAR_KEY] = rv

    return params_and_grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """d(targets)/d(inputs) as new grad vars (reference backward.py:1199)."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    program = targets[0].block.program
    block = program.global_block()
    no_grad = _collect_no_grad(block, no_grad_set)
    input_names = {v.name for v in inputs}

    with program._backward_role_guard():
        from .ops.common import dtype_enum

        grad_op_descs = []
        for i, t in enumerate(targets):
            tg_name = _grad_var_name(t.name)
            if target_gradients is not None and target_gradients[i] is not None:
                tg = target_gradients[i]
                block.append_op(
                    type="assign",
                    inputs={"X": [tg.name]},
                    outputs={"Out": [tg_name]},
                )
                block.create_var(name=tg_name, shape=t.shape, dtype=t.dtype)
            else:
                block.create_var(name=tg_name, shape=t.shape, dtype=t.dtype)
                block.append_op(
                    type="fill_constant",
                    outputs={"Out": [tg_name]},
                    attrs={
                        "shape": list(t.shape or (1,)),
                        "value": 1.0,
                        "dtype": dtype_enum(t.dtype or "float32"),
                    },
                )

        relevant_all = set()
        flow_all = set()
        for t in targets:
            rel, flow = _relevant_ops(block, t.name, no_grad)
            relevant_all |= set(rel)
            flow_all |= flow
        gops_with_def = []
        for idx in sorted(relevant_all, reverse=True):
            op = block.ops[idx]
            opdef = get_op_def(op.type)
            ng = no_grad | {n for n in op.input_arg_names
                            if n and n not in flow_all}
            for gop in opdef.make_grad_ops(op, ng):
                gops_with_def.append((opdef, gop))

        # Second-and-later differentiation passes (double-grad: the block
        # already holds grad ops from an earlier append_backward/gradients
        # call) reuse @GRAD names; a pass-local gradient that collides with
        # an existing var would silently alias the *previous* pass's
        # gradient.  Rename pass-local gradients consistently (the
        # reference's calc_gradient does this via _rename_grad_,
        # backward.py:1199).  Pass-local = any gop output, plus any input in
        # a "GRAD@<out_slot>" slot (the upstream gradient flowing within
        # this pass) — other input slots reference existing forward vars.
        created = {_grad_var_name(t.name) for t in targets}
        rename = {}

        def _fresh(n):
            k = 2
            while True:
                cand = "%s@D%d" % (n, k)
                if not block.has_var_recursive(cand) and cand not in rename.values():
                    return cand
                k += 1

        local = set()
        for opdef, gop in gops_with_def:
            for names in gop.outputs.values():
                local.update(n for n in names if n)
            for slot, names in gop.inputs.items():
                if slot.startswith("GRAD@") and slot[5:] in opdef.output_slots:
                    local.update(n for n in names if n)
        for n in sorted(local):
            if n not in created and block.has_var_recursive(n):
                rename[n] = _fresh(n)

        # Apply the map in emission order.  An input is pass-local when its
        # slot carries the upstream gradient ("GRAD@<out_slot>") OR when an
        # earlier grad op of this pass already produced that name — the
        # latter catches hand-written grad makers that pipe gradients
        # through generic ops (e.g. the quant STE's assign), whose slot
        # names say nothing about gradient-ness.  Grad ops are emitted in
        # reverse topological order, so a consumer of a pass-local gradient
        # always follows its producer.
        grad_op_descs = []
        produced = set()
        for opdef, gop in gops_with_def:
            for slot, names in list(gop.inputs.items()):
                is_grad_slot = (slot.startswith("GRAD@")
                                and slot[5:] in opdef.output_slots)
                gop.inputs[slot] = [
                    rename.get(n, n) if (is_grad_slot or n in produced) else n
                    for n in names
                ]
            for slot, names in list(gop.outputs.items()):
                produced.update(n for n in names if n)
                gop.outputs[slot] = [rename.get(n, n) for n in names]
            grad_op_descs.append(gop)

        grad_op_descs = _dedup_grad_ops(grad_op_descs)
        for gop in grad_op_descs:
            _append_grad_op(block, gop, {})

    outs = []
    for v in inputs:
        gname = _grad_var_name(v.name)
        gname = rename.get(gname, gname)
        outs.append(block.var(gname) if block.has_var_recursive(gname) else None)
    return outs


calc_gradient = gradients
