"""Sequence layer API over padded batches + lengths.

Parity: python/paddle/fluid/layers/sequence_lod.py (sequence_pool :331,
sequence_conv :30, sequence_softmax :235, sequence_expand :650,
sequence_pad :960, sequence_unpad :1055, sequence_reverse, sequence_concat
:553, sequence_first_step :441, sequence_last_step :487, sequence_mask).

See ops/sequence.py for the LoD→padded+mask design rationale.  Every layer
takes an optional ``seq_len`` (per-row lengths, [B] int tensor) in place of
the reference's hidden LoD metadata.
"""

from ..layer_helper import LayerHelper

__all__ = [
    "sequence_conv",
    "sequence_pool",
    "sequence_softmax",
    "sequence_expand",
    "sequence_expand_as",
    "sequence_pad",
    "sequence_unpad",
    "sequence_reverse",
    "sequence_concat",
    "sequence_first_step",
    "sequence_last_step",
    "sequence_mask",
    "sequence_enumerate",
]


def _seq_inputs(x, seq_len, extra=None):
    inputs = {"X": [x]}
    if seq_len is not None:
        inputs["Length"] = [seq_len]
    if extra:
        inputs.update(extra)
    return inputs


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0, seq_len=None):
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="sequence_pool",
        inputs=_seq_inputs(input, seq_len),
        outputs={"Out": [out]},
        attrs={"pooltype": pool_type.upper(), "pad_value": float(pad_value)},
    )
    return out


def sequence_first_step(input, seq_len=None):
    return sequence_pool(input, "first", seq_len=seq_len)


def sequence_last_step(input, seq_len=None):
    return sequence_pool(input, "last", seq_len=seq_len)


def sequence_softmax(input, use_cudnn=False, name=None, seq_len=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="sequence_softmax",
        inputs=_seq_inputs(input, seq_len),
        outputs={"Out": [out]},
    )
    return out


def sequence_expand(x, y, ref_level=-1, name=None, ref_length=None):
    """`ref_length` (optional [B] Variable) carries the chosen LoD level's
    true per-sample counts so the expansion masks the padded tail (the
    multi-level LoD path; see ops/sequence.py:sequence_expand)."""
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if ref_length is not None:
        inputs["RefLength"] = [ref_length]
    helper.append_op(
        type="sequence_expand", inputs=inputs,
        outputs={"Out": [out]}, attrs={"ref_level": ref_level},
    )
    return out


def sequence_expand_as(x, y, name=None):
    helper = LayerHelper("sequence_expand_as", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="sequence_expand_as", inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
    )
    return out


def sequence_pad(x, pad_value, maxlen=None, name=None, seq_len=None):
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    length = helper.create_variable_for_type_inference(dtype="int64")
    inputs = {"X": [x], "PadValue": [pad_value]}
    if seq_len is not None:
        inputs["Length"] = [seq_len]
    helper.append_op(
        type="sequence_pad", inputs=inputs,
        outputs={"Out": [out], "Length@OUT": [length]},
        attrs={"padded_length": -1 if maxlen is None else int(maxlen)},
    )
    return out, length


def sequence_unpad(x, length, name=None):
    helper = LayerHelper("sequence_unpad", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="sequence_unpad", inputs={"X": [x], "Length": [length]},
        outputs={"Out": [out]},
    )
    return out


def sequence_reverse(x, name=None, seq_len=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="sequence_reverse",
        inputs=_seq_inputs(x, seq_len),
        outputs={"Y": [out]},
    )
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", name=name)
    out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op(
        type="sequence_concat", inputs={"X": list(input)},
        outputs={"Out": [out]},
    )
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None, seq_len=None):
    helper = LayerHelper("sequence_conv", name=name, bias_attr=bias_attr,
                         param_attr=param_attr, act=act)
    dtype = input.dtype
    filter_shape = [filter_size * input.shape[-1], num_filters]
    filter_param = helper.create_parameter(
        attr=helper.kwargs.get("param_attr"), shape=filter_shape, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    if padding_start is None:
        padding_start = -int(filter_size // 2)
    inputs = _seq_inputs(input, seq_len, {"Filter": [filter_param]})
    helper.append_op(
        type="sequence_conv",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={"contextStride": filter_stride, "contextStart": padding_start,
               "contextLength": filter_size},
    )
    out = helper.append_bias_op(out, dim_start=2, dim_end=3)
    return helper.append_activation(out)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ..ops.common import dtype_enum

    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="sequence_mask", inputs={"X": [x]}, outputs={"Y": [out]},
        attrs={"maxlen": -1 if maxlen is None else int(maxlen),
               "out_dtype": dtype_enum(dtype)},
    )
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    helper = LayerHelper("sequence_enumerate", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="sequence_enumerate", inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"win_size": int(win_size), "pad_value": int(pad_value)},
    )
    return out
