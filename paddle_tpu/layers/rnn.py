"""RNN / decoding layers: beam search, GRU/LSTM units.

Parity: python/paddle/fluid/layers/rnn.py + layers/nn.py beam_search
(wrapping operators/beam_search_op.cc) and the dynamic/static RNN units.
"""

import numpy as np

from ..layer_helper import LayerHelper

__all__ = ["beam_search", "beam_search_decode", "gru_unit", "lstm_unit",
           "dynamic_lstmp", "lstm",
           "dynamic_gru", "dynamic_lstm",
           "RNNCell", "GRUCell", "LSTMCell", "rnn", "dynamic_decode",
           "BeamSearchDecoder"]


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=True):
    """One beam expansion step on dense [batch, beam] state (see
    ops/beam_search.py for the LoD→dense mapping)."""
    helper = LayerHelper("beam_search", name=name)
    selected_ids = helper.create_variable_for_type_inference(dtype=pre_ids.dtype)
    selected_scores = helper.create_variable_for_type_inference(
        dtype=scores.dtype)
    parent_idx = helper.create_variable_for_type_inference(dtype=pre_ids.dtype)
    inputs = {"pre_ids": [pre_ids], "pre_scores": [pre_scores],
              "scores": [scores]}
    if ids is not None:
        inputs["ids"] = [ids]
    helper.append_op(
        type="beam_search",
        inputs=inputs,
        outputs={"selected_ids": [selected_ids],
                 "selected_scores": [selected_scores],
                 "parent_idx": [parent_idx]},
        attrs={"beam_size": beam_size, "end_id": end_id, "level": level,
               "is_accumulated": is_accumulated},
    )
    if return_parent_idx:
        return selected_ids, selected_scores, parent_idx
    return selected_ids, selected_scores


def beam_search_decode(ids, parent_idx, scores=None, beam_size=4, end_id=1,
                       name=None):
    """Backtrack tensor arrays of (ids, parents) into sequences."""
    helper = LayerHelper("beam_search_decode", name=name)
    sentence_ids = helper.create_variable_for_type_inference(dtype="int64")
    sentence_scores = helper.create_variable_for_type_inference(dtype="float32")
    inputs = {"Ids": [ids], "ParentIdx": [parent_idx]}
    if scores is not None:
        inputs["Scores"] = [scores]
    helper.append_op(
        type="beam_search_decode",
        inputs=inputs,
        outputs={"SentenceIds": [sentence_ids],
                 "SentenceScores": [sentence_scores]},
        attrs={"beam_size": beam_size, "end_id": end_id},
    )
    return sentence_ids, sentence_scores


def _act(op_type, x):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", name=None):
    """GRU cell step (reference operators/gru_unit_op.cc): input is the
    projected step input [B, 3*D], hidden [B, D]."""
    from . import nn

    D = size // 3
    gates_w = nn.fc(hidden, 2 * D, param_attr=param_attr,
                    bias_attr=bias_attr, name=(name or "gru") + "_gates")
    xu, xr, xc = nn.split(input, 3, dim=-1)
    hu, hr = nn.split(gates_w, 2, dim=-1)
    u = _act(gate_activation, xu + hu)
    r = _act(gate_activation, xr + hr)
    cand_h = nn.fc(hidden * r, D, param_attr=param_attr,
                   bias_attr=False, name=(name or "gru") + "_cand")
    c = _act(activation, xc + cand_h)
    new_hidden = u * hidden + (1.0 - u) * c
    return new_hidden, new_hidden, c


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """LSTM cell step (reference layers/nn.py lstm_unit)."""
    from . import nn, tensor

    D = hidden_t_prev.shape[-1]
    concat_in = tensor.concat([x_t, hidden_t_prev], axis=-1)
    gates = nn.fc(concat_in, 4 * D, param_attr=param_attr,
                  bias_attr=bias_attr, name=(name or "lstm") + "_gates")
    i, f, c, o = nn.split(gates, 4, dim=-1)
    i = _act("sigmoid", i)
    f = _act("sigmoid", f + forget_bias)
    o = _act("sigmoid", o)
    c = _act("tanh", c)
    new_cell = f * cell_t_prev + i * c
    new_hidden = o * _act("tanh", new_cell)
    return new_hidden, new_cell


def dynamic_gru(input, size, seq_len=None, h_0=None, reverse=False,
                param_attr=None, bias_attr=None, name=None):
    """GRU over the time axis via StaticRNN/lax.scan (reference
    operators/gru_op.cc; LoD ragged input becomes padded + seq_len mask)."""
    from .control_flow import StaticRNN
    from . import nn, tensor

    name = name or "dynamic_gru"
    proj = nn.fc(input, 3 * size, num_flatten_dims=2, param_attr=param_attr,
                 bias_attr=bias_attr, name=name + "_proj")
    proj_t = nn.transpose(proj, [1, 0, 2])  # [T, B, 3D]
    rnn = StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(proj_t)
        h_prev = rnn.memory(init=h_0, shape=(-1, size), batch_ref=input,
                            init_value=0.0, ref_batch_dim_idx=0)
        h, _, _ = gru_unit(x_t, h_prev, 3 * size, name=name)
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    out = rnn()  # [T, B, D]
    return nn.transpose(out, [1, 0, 2])


def dynamic_lstm(input, size, seq_len=None, h_0=None, c_0=None,
                 reverse=False, param_attr=None, bias_attr=None, name=None,
                 return_cell=False):
    """LSTM over the time axis via StaticRNN/lax.scan (reference
    operators/lstm_op.cc).  With return_cell=True also returns the cell
    trajectory [B, T, D] (consumed by layers.lstm for last_c)."""
    from .control_flow import StaticRNN
    from . import nn

    name = name or "dynamic_lstm"
    D = size // 4
    x_t_all = nn.transpose(input, [1, 0, 2])  # [T, B, F]
    rnn = StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x_t_all)
        h_prev = rnn.memory(init=h_0, shape=(-1, D), batch_ref=input,
                            init_value=0.0, ref_batch_dim_idx=0)
        c_prev = rnn.memory(init=c_0, shape=(-1, D), batch_ref=input,
                            init_value=0.0, ref_batch_dim_idx=0)
        h, c = lstm_unit(x_t, h_prev, c_prev, name=name)
        rnn.update_memory(h_prev, h)
        rnn.update_memory(c_prev, c)
        rnn.step_output(h)
        if return_cell:
            rnn.step_output(c)
    if return_cell:
        out, cells = rnn()
        return nn.transpose(out, [1, 0, 2]), nn.transpose(cells, [1, 0, 2])
    out = rnn()
    return nn.transpose(out, [1, 0, 2])


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=False, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, h_0=None, c_0=None,
                  cell_clip=None, proj_clip=None):
    """LSTM with a projection layer (reference operators/lstmp_op.cc):
    the recurrent state is the projection r [B, P] of the hidden state."""
    from .control_flow import StaticRNN
    from . import nn

    name = name or "dynamic_lstmp"
    D = size // 4
    x = _reverse_time(input) if is_reverse else input
    x_t_all = nn.transpose(x, [1, 0, 2])
    rnn = StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x_t_all)
        r_prev = rnn.memory(init=h_0, shape=(-1, proj_size), batch_ref=input,
                            init_value=0.0, ref_batch_dim_idx=0)
        c_prev = rnn.memory(init=c_0, shape=(-1, D), batch_ref=input,
                            init_value=0.0, ref_batch_dim_idx=0)
        # lstmp cell: gates sized by D (cell width), recurrent input is
        # the projection r_prev [B, P]
        from . import tensor as _T

        gates = nn.fc(_T.concat([x_t, r_prev], axis=-1), 4 * D,
                      param_attr=param_attr, bias_attr=bias_attr,
                      name=name + "_gates")
        gi, gf, gc, go = nn.split(gates, 4, dim=-1)
        gi = _act(gate_activation, gi)
        gf = _act(gate_activation, gf)
        go = _act(gate_activation, go)
        gc = _act(candidate_activation, gc)
        c = gf * c_prev + gi * gc
        h = go * _act(cell_activation, c)
        # projection weight must be a DISTINCT parameter from the gates
        # (a shared named ParamAttr would alias two different shapes)
        proj_attr = None
        if param_attr is not None and getattr(param_attr, "name", None):
            from ..param_attr import ParamAttr as _PA

            proj_attr = _PA(name=param_attr.name + "_proj")
        r = nn.fc(h, proj_size, param_attr=proj_attr, bias_attr=False,
                  act=proj_activation, name=name + "_proj")
        rnn.update_memory(r_prev, r)
        rnn.update_memory(c_prev, c)
        rnn.step_output(r)
        rnn.step_output(c)
    proj_out, cells = rnn()
    proj_out = nn.transpose(proj_out, [1, 0, 2])
    cells = nn.transpose(cells, [1, 0, 2])
    if is_reverse:
        proj_out = _reverse_time(proj_out)
        cells = _reverse_time(cells)
    return proj_out, cells


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Stacked (cuDNN-style) LSTM (reference operators/cudnn_lstm_op.cu):
    input [B, T, F] -> (out [B, T, H or 2H], last_h, last_c).  Composed
    from per-layer dynamic_lstm scans; bidirectional runs a reversed pass
    and concatenates.  init_h/init_c accepted for API parity (zero state
    when None)."""
    from . import nn

    name = name or "lstm"
    x = input
    for layer in range(num_layers):
        # lstm_unit projects concat(x, h) itself — no input fc needed
        fwd, fwd_c = dynamic_lstm(
            x, 4 * hidden_size, name="%s_l%d_fwd" % (name, layer),
            return_cell=True)
        if is_bidirec:
            bwd, bwd_c = dynamic_lstm(
                _reverse_time(x), 4 * hidden_size,
                name="%s_l%d_bwd" % (name, layer), return_cell=True)
            x = nn.concat([fwd, _reverse_time(bwd)], axis=2)
        else:
            x = fwd
        if dropout_prob and not is_test and layer + 1 < num_layers:
            x = nn.dropout(x, dropout_prob,
                           dropout_implementation="upscale_in_train")
    T = fwd.shape[1]

    def _last(t):  # final recurrent state = step T-1 in scan order
        return nn.slice(t, axes=[1], starts=[T - 1], ends=[T])

    if is_bidirec:
        # bwd's final state (after consuming the whole sequence) is its own
        # step T-1, which sits at index 0 AFTER un-reversal — slice the
        # pre-reversal trajectory instead
        last_h = nn.concat([_last(fwd), _last(bwd)], axis=2)
        last_c = nn.concat([_last(fwd_c), _last(bwd_c)], axis=2)
    else:
        last_h, last_c = _last(fwd), _last(fwd_c)
    return x, last_h, last_c


def _reverse_time(x):
    """Reverse a [B, T, D] tensor along the time axis (reverse op)."""
    from ..layer_helper import LayerHelper

    helper = LayerHelper("reverse_time")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="reverse", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": [1]})
    out.shape = x.shape
    return out


# ---------------------------------------------------------------------------
# Cell-class API (reference layers/rnn.py: RNNCell/GRUCell/LSTMCell, rnn(),
# dynamic_decode) — class-based recurrence over the StaticRNN/lax.scan
# machinery.
# ---------------------------------------------------------------------------


def _derived_attr(attr, suffix):
    """Distinct parameter per use-site: a shared named ParamAttr would alias
    differently-shaped weights (same hazard as dynamic_lstmp's projection)."""
    if attr is None or getattr(attr, "name", None) is None:
        return attr
    from ..param_attr import ParamAttr as _PA

    return _PA(name=attr.name + suffix)


class RNNCell:
    """Base cell: call(inputs, states) -> (outputs, new_states)."""

    def call(self, inputs, states):
        raise NotImplementedError

    def __call__(self, inputs, states):
        return self.call(inputs, states)

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        from . import tensor as T

        shape = list(shape or [self.hidden_size])
        return T.fill_constant_batch_size_like(
            batch_ref, [-1] + shape, dtype, init_value,
            input_dim_idx=batch_dim_idx)

    @property
    def state_shape(self):
        return [self.hidden_size]


class GRUCell(RNNCell):
    """GRU cell (reference layers/rnn.py GRUCell over gru_unit)."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation="sigmoid", activation="tanh",
                 dtype="float32", name="GRUCell"):
        self.hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._gate_activation = gate_activation or "sigmoid"
        self._activation = activation or "tanh"
        self._name = name

    def call(self, inputs, states):
        from . import nn

        proj = nn.fc(inputs, 3 * self.hidden_size,
                     param_attr=_derived_attr(self._param_attr, "_in"),
                     bias_attr=self._bias_attr, name=self._name + "_in")
        h, _, _ = gru_unit(proj, states, 3 * self.hidden_size,
                           param_attr=_derived_attr(self._param_attr, "_rec"),
                           bias_attr=self._bias_attr,
                           activation=self._activation,
                           gate_activation=self._gate_activation,
                           name=self._name)
        return h, h


class LSTMCell(RNNCell):
    """LSTM cell (reference layers/rnn.py LSTMCell over lstm_unit);
    states = [hidden, cell]."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, forget_bias=1.0,
                 dtype="float32", name="LSTMCell"):
        if gate_activation not in (None, "sigmoid") or activation not in (
                None, "tanh"):
            raise NotImplementedError(
                "LSTMCell supports only sigmoid gates / tanh activation "
                "(lstm_unit's fixed nonlinearity)")
        self.hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._forget_bias = forget_bias
        self._name = name

    def call(self, inputs, states):
        h_prev, c_prev = states
        h, c = lstm_unit(inputs, h_prev, c_prev,
                         forget_bias=self._forget_bias,
                         param_attr=self._param_attr,
                         bias_attr=self._bias_attr, name=self._name)
        return h, [h, c]

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        mk = super().get_initial_states
        return [mk(batch_ref, shape, dtype, init_value, batch_dim_idx),
                mk(batch_ref, shape, dtype, init_value, batch_dim_idx)]

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Run `cell` over the time axis (reference layers/rnn.py rnn):
    inputs [B, T, F] (or [T, B, F] when time_major).  Returns
    (outputs, final_states) with final_states mirroring the cell's state
    structure ([B, H], or [h, c] for LSTM)."""
    from .control_flow import StaticRNN
    from . import nn

    if sequence_length is not None:
        raise NotImplementedError(
            "rnn(): sequence_length masking is not implemented — pad-safe "
            "models should mask outputs downstream (sequence ops) instead")
    # state batch dim comes from the BATCH axis of inputs: 0 normally,
    # 1 when time_major
    batch_dim = 1 if time_major else 0
    if is_reverse:
        # reverse along the time axis before going time-major (single
        # transpose; the outputs are un-reversed below)
        if time_major:
            x_bt = nn.transpose(inputs, [1, 0, 2])
            x = nn.transpose(_reverse_time(x_bt), [1, 0, 2])
        else:
            x = nn.transpose(_reverse_time(inputs), [1, 0, 2])
    else:
        x = inputs if time_major else nn.transpose(inputs, [1, 0, 2])
    multi_state = isinstance(cell.state_shape[0], (list, tuple))

    srnn = StaticRNN()
    with srnn.step():
        x_t = srnn.step_input(x)
        if multi_state:
            shapes = cell.state_shape
            inits = initial_states or [None] * len(shapes)
            states = [srnn.memory(init=inits[i], shape=(-1, shapes[i][0]),
                                  batch_ref=inputs, init_value=0.0,
                                  ref_batch_dim_idx=batch_dim)
                      for i in range(len(shapes))]
            out, new_states = cell.call(x_t, states)
            for s, ns in zip(states, new_states):
                srnn.update_memory(s, ns)
            srnn.step_output(out)
            for ns in new_states:
                srnn.step_output(ns)
        else:
            state = srnn.memory(init=initial_states,
                                shape=(-1, cell.state_shape[0]),
                                batch_ref=inputs, init_value=0.0,
                                ref_batch_dim_idx=batch_dim)
            out, new_state = cell.call(x_t, state)
            srnn.update_memory(state, new_state)
            srnn.step_output(out)
            srnn.step_output(new_state)
    results = srnn()
    if not isinstance(results, (list, tuple)):
        results = [results]
    outs = results[0]                       # [T, B, H]
    state_trajs = results[1:]
    T_len = outs.shape[0]

    def _final(traj):  # last SCAN step = final recurrent state, [B, H]
        last = nn.slice(traj, axes=[0], starts=[T_len - 1], ends=[T_len])
        return nn.squeeze(last, [0])

    final_states = [_final(t) for t in state_trajs]
    outs_bt = nn.transpose(outs, [1, 0, 2])
    if is_reverse:
        outs_bt = _reverse_time(outs_bt)
    result = outs_bt if not time_major else nn.transpose(outs_bt, [1, 0, 2])
    if multi_state:
        return result, final_states
    return result, final_states[0]


def dynamic_decode(decoder, inits=None, max_step_num=None, **kwargs):
    """Greedy unrolled decode (reference layers/rnn.py dynamic_decode with
    a Decoder implementing initialize/step).  Static unroll to
    max_step_num (XLA static shapes).  Once a sample's `finished` flag is
    set its states are frozen (the reference's _maybe_copy); outputs after
    finish repeat the step output and should be masked by the caller.
    Returns (outputs [B, T, ...], final_states)."""
    from . import nn, tensor as T

    if max_step_num is None:
        raise ValueError("dynamic_decode requires max_step_num on TPU "
                         "(static shapes)")
    inputs, states, _ = decoder.initialize(inits)
    step_outputs = []
    fin = None

    def _freeze(old, new):
        if fin is None:
            return new
        # fluid broadcast: fin [B,1] onto state [B,H] aligns at axis=0
        keep = nn.elementwise_mul(old, fin, axis=0)
        upd = nn.elementwise_mul(new, 1.0 - fin, axis=0)
        out = nn.elementwise_add(keep, upd)
        out.shape = new.shape
        return out

    for t in range(int(max_step_num)):
        out, new_states, inputs, finished = decoder.step(t, inputs, states)
        if isinstance(new_states, (list, tuple)):
            states = [_freeze(o, n) for o, n in zip(states, new_states)]
        else:
            states = _freeze(states, new_states)
        if finished is not None:
            f = T.cast(finished, "float32")
            fin = f if fin is None else nn.elementwise_max(fin, f)
        step_outputs.append(nn.unsqueeze(out, [1]))
    outputs = T.concat(step_outputs, axis=1)
    return outputs, states


class BeamSearchDecoder:
    """Beam-search decoder for dynamic_decode (reference layers/rnn.py
    BeamSearchDecoder): wraps an RNNCell; each step expands K beams over the
    vocab, keeps the top K continuations, and tracks parent pointers for
    gather_tree backtracking.

    Works on flattened [B*K, ...] tensors.  step() emits
    concat([token_ids, parent_ids], axis=1) as its per-step output
    ([B, 2K]); finalize() splits them and backtracks with gather_tree.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers -------------------------------------------------------------
    def _merge(self, x):      # [B, K, ...] -> [B*K, ...]
        from . import nn

        shape = [-1] + [int(d) for d in x.shape[2:]]
        out = nn.reshape(x, shape)
        out.shape = tuple(shape)
        return out

    def _split(self, x):      # [B*K, ...] -> [B, K, ...]
        from . import nn

        shape = [-1, self.beam_size] + [int(d) for d in x.shape[1:]]
        out = nn.reshape(x, shape)
        out.shape = tuple(shape)
        return out

    def initialize(self, inits):
        """inits: cell initial states with batch dim B (single tensor or
        list).  Tiles everything beam_size times.  Decoder state layout:
        [*cell_states, logp [B*K,1], last_tokens [B*K,1]]."""
        from . import nn, tensor as T

        K = self.beam_size
        states = inits if isinstance(inits, (list, tuple)) else [inits]

        def tile(s):  # [B, H] -> [B*K, H]
            e = nn.unsqueeze(s, [1])
            e.shape = (s.shape[0], 1) + tuple(s.shape[1:])
            e = nn.expand(e, [1, K, 1])
            e.shape = (s.shape[0], K) + tuple(s.shape[1:])
            return self._merge(e)

        tiled = [tile(s) for s in states]
        b = states[0]
        # log-prob state [B*K, 1]: beam 0 starts at 0, others at -inf so
        # the first expansion draws only from beam 0.  Built as an outer
        # product ones[B,1] @ bias[1,K] (fluid's y-broadcast rules cannot
        # express a leading-1 bias add)
        ones_col = T.fill_constant_batch_size_like(b, [-1, 1], "float32",
                                                   1.0)
        beam_bias = T.assign(
            np.array([[0.0] + [-1e9] * (K - 1)], "float32"))   # [1, K]
        logp = nn.reshape(nn.matmul(ones_col, beam_bias), [-1, 1])
        logp.shape = (-1, 1)
        start = T.fill_constant_batch_size_like(
            logp, [-1, 1], "int64", self.start_token)
        start.shape = (-1, 1)
        inputs = self.embedding_fn(start) if self.embedding_fn else start
        return inputs, tiled + [logp, start], None

    def step(self, time, inputs, states):
        from . import nn, tensor as T

        K = self.beam_size
        cell_states, logp, last_tok = states[:-2], states[-2], states[-1]
        cs = cell_states if len(cell_states) > 1 else cell_states[0]
        out, new_states = self.cell.call(inputs, cs)
        if not isinstance(new_states, (list, tuple)):
            new_states = [new_states]
        logits = self.output_fn(out) if self.output_fn else out
        lp_step = nn.log_softmax(logits)                 # [B*K, V]
        lp_step.shape = logits.shape
        V = int(lp_step.shape[-1])
        # fluid broadcast: y [B*K,1] onto x [B*K,V] aligns at axis=0
        total = nn.elementwise_add(lp_step, logp, axis=0)
        total.shape = lp_step.shape
        total3 = nn.reshape(total, [-1, K, V])
        total3.shape = (-1, K, V)
        pre_ids = nn.reshape(last_tok, [-1, K])
        pre_ids.shape = (-1, K)
        pre_scores = nn.reshape(logp, [-1, K])
        pre_scores.shape = (-1, K)
        # the beam_search op owns selection AND finished-beam semantics:
        # a beam whose last token is end_id emits only end_id with its
        # score unchanged (ops/beam_search.py)
        tokens, sel_scores, parents = beam_search(
            pre_ids, pre_scores, None, total3, K, self.end_token)
        tokens.shape = parents.shape = sel_scores.shape = (-1, K)

        def gather_beams(s):  # s: [B*K, H] -> [B*K, H] reordered
            sk = self._split(s)                          # [B, K, H]
            return self._merge(_batched_gather(sk, parents))

        new_states = [gather_beams(s) for s in new_states]
        sv = nn.unsqueeze(sel_scores, [2])
        sv.shape = (-1, K, 1)
        new_logp = self._merge(sv)                       # [B*K, 1]
        tok_flat = nn.reshape(tokens, [-1, 1])           # [B*K, 1]
        tok_flat.shape = (-1, 1)
        inputs = self.embedding_fn(tok_flat) if self.embedding_fn else \
            T.cast(tok_flat, "float32")
        out_pair = nn.concat([tokens, parents], axis=1)  # [B, 2K]
        # finished handling lives inside the beam_search op; no positional
        # freeze (beams are reordered every step, a positional mask would
        # clobber live beams)
        return out_pair, new_states + [new_logp, tok_flat], inputs, None

    def finalize(self, outputs):
        """outputs [B, T, 2K] from dynamic_decode -> (sequences [T, B, K],
        final beam scores are in the last state)."""
        from . import nn

        K = self.beam_size
        ids = nn.transpose(nn.slice(outputs, axes=[2], starts=[0],
                                    ends=[K]), [1, 0, 2])      # [T, B, K]
        parents = nn.transpose(nn.slice(outputs, axes=[2], starts=[K],
                                        ends=[2 * K]), [1, 0, 2])
        from .extra import gather_tree

        return gather_tree(ids, parents)


def _batched_gather(x, idx):
    """x [B, K, ...], idx [B, K] -> x[b, idx[b, k]] via one-hot matmul
    (XLA-friendly, avoids gather_nd index building)."""
    from . import nn, tensor as T

    K = int(x.shape[1])
    # one_hot follows fluid's trailing-1 replacement rule: feed [B, K, 1]
    # so the output is [B, K, K] for every K (incl. K=1)
    idx3 = nn.unsqueeze(idx, [2])
    idx3.shape = (-1, K, 1)
    oh = nn.one_hot(idx3, K)                 # [B, K, K]
    oh.shape = (-1, K, K)
    flat = nn.reshape(x, [0, K, -1])         # [B, K, H]
    out = nn.matmul(oh, flat)                # [B, K, H]
    shape = [0, K] + [int(d) for d in x.shape[2:]]
    out2 = nn.reshape(out, shape)
    out2.shape = tuple([-1] + list(shape[1:]))
    return out2
