"""Control-flow layer API: While, StaticRNN, Switch, IfElse, tensor arrays.

Parity: python/paddle/fluid/layers/control_flow.py (While :1024, Switch
:1721, IfElse :2193, StaticRNN :417, array_write :1373, array_read :1518,
increment :1335, array_length :1589).

On TPU these build sub-blocks that the executor lowers to trace-time
unrolling, `lax.while_loop`, `lax.cond`, or `lax.scan` (see
ops/control_flow.py for the lowering rules).
"""

import contextlib

from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper
from ..utils import unique_name

__all__ = [
    "While",
    "Switch",
    "IfElse",
    "StaticRNN",
    "increment",
    "array_write",
    "array_read",
    "array_length",
    "create_array",
    "less_than",
    "less_equal",
    "greater_than",
    "greater_equal",
    "equal",
    "not_equal",
    "cond",
    "is_empty",
    "Print",
]


@contextlib.contextmanager
def _sub_block(program):
    block = program._create_block()
    try:
        yield block
    finally:
        program._rollback()


def _collect_captures(blk, parent, skip=()):
    """(captured, out_names) for a completed sub-block.

    captured = names read before any in-block write (excluding `skip`) —
    includes parameters created inside the block, which live in the global
    scope.  out_names = names written by the block that exist outside it
    (the vars the enclosing op "returns").
    """
    writes = set()
    captured = []
    skip = set(skip)
    for op in blk.ops:
        for n in op.input_arg_names:
            if n and n not in writes and n not in skip and n not in captured:
                captured.append(n)
        for n in op.output_arg_names:
            if n:
                writes.add(n)
    out_names = sorted(
        n for n in writes
        if parent.has_var_recursive(n) and not blk.has_var(n)
    )
    return captured, out_names


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="increment", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"step": float(value)},
    )
    return out


def create_array(dtype):
    helper = LayerHelper("array")
    return helper.main_program.current_block().create_var(
        name=unique_name.generate("array"), dtype=dtype, shape=None,
        type="LOD_TENSOR_ARRAY",
    )


def array_write(x, i, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    inputs = {"X": [x], "I": [i], "Array": [array]}
    helper.append_op(
        type="write_to_array", inputs=inputs, outputs={"Out": [array]}
    )
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(dtype=array.dtype)
    helper.append_op(
        type="read_from_array", inputs={"X": [array], "I": [i]},
        outputs={"Out": [out]},
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference(dtype="int64")
    out.shape = ()
    helper.append_op(
        type="lod_array_length", inputs={"X": [array]}, outputs={"Out": [out]}
    )
    return out


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    out = cond or helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="is_empty", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def Print(input, first_n=-1, message=None, summarize=20, **kwargs):
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="print", inputs={"In": [input]}, outputs={"Out": [out]},
        attrs={"message": message or "", "first_n": first_n,
               "summarize": summarize},
    )
    return out


# comparison layers (the reference keeps these in layers.control_flow)
def _make_compare(op_type):
    def layer(x, y, cond=None, force_cpu=None):
        helper = LayerHelper(op_type)
        out = cond or helper.create_variable_for_type_inference(dtype="bool")
        helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]})
        return out

    layer.__name__ = op_type
    return layer


less_than = _make_compare("less_than")
less_equal = _make_compare("less_equal")
greater_than = _make_compare("greater_than")
greater_equal = _make_compare("greater_equal")
equal = _make_compare("equal")
not_equal = _make_compare("not_equal")


class While:
    """``with While(cond).block(): ...`` — loop while `cond` is true.  The
    body must update `cond` (reference layers/control_flow.py:1024)."""

    def __init__(self, cond, is_test=False, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.is_test = is_test

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent = program.current_block()
        with _sub_block(program) as blk:
            yield
        captured, out_names = _collect_captures(blk, parent)
        parent.append_op(
            type="while",
            inputs={"X": captured, "Condition": [self.cond_var]},
            outputs={"Out": out_names, "StepScopes": []},
            attrs={"sub_block": blk.idx, "is_test": self.is_test},
        )


class Switch:
    """``with switch.case(cond): ...`` / ``with switch.default(): ...``
    (reference layers/control_flow.py:1721).  Lowers to a chain of
    conditional_block ops with not-any-previous predicates."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.inside_scope = False
        self.pre_not_conditions = []

    @contextlib.contextmanager
    def case(self, condition):
        from . import tensor as ltensor

        if len(self.pre_not_conditions) == 0:
            cond = condition
            not_cond = logical_not_layer(condition)
        else:
            pre = self.pre_not_conditions[-1]
            cond = logical_and_layer(pre, condition)
            not_cond = logical_and_layer(pre, logical_not_layer(condition))
        self.pre_not_conditions.append(not_cond)
        with _cond_block(self.helper, cond):
            yield

    @contextlib.contextmanager
    def default(self):
        if not self.pre_not_conditions:
            raise ValueError("default() must follow at least one case()")
        with _cond_block(self.helper, self.pre_not_conditions[-1]):
            yield


def logical_not_layer(x):
    helper = LayerHelper("logical_not")
    out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="logical_not", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def logical_and_layer(x, y):
    helper = LayerHelper("logical_and")
    out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="logical_and", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


@contextlib.contextmanager
def _cond_block(helper, condition):
    program = helper.main_program
    parent = program.current_block()
    with _sub_block(program) as blk:
        yield
    captured, out_names = _collect_captures(blk, parent)
    parent.append_op(
        type="conditional_block",
        inputs={"Cond": [condition], "Input": captured},
        outputs={"Out": out_names, "Scope": []},
        attrs={"sub_block": blk.idx, "is_scalar_condition": True},
    )


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Functional two-branch conditional built from two conditional_blocks
    writing the same output vars (2.x-style convenience; the reference 1.6
    equivalent is IfElse)."""
    from . import tensor as ltensor

    helper = LayerHelper("cond", name=name)
    true_out = None
    false_out = None
    # stage both branches into assigns onto shared output vars
    results = {}

    def run_branch(fn, condition):
        nonlocal results
        with _cond_block(helper, condition):
            out = fn()
            outs = out if isinstance(out, (list, tuple)) else [out]
            for i, o in enumerate(outs):
                if i not in results:
                    results[i] = helper.main_program.current_block().\
                        parent_block.create_var(
                            name=unique_name.generate("cond_out"),
                            dtype=o.dtype, shape=o.shape)
                helper.append_op(type="assign", inputs={"X": [o]},
                                 outputs={"Out": [results[i]]})
            return len(outs)

    n_true = run_branch(true_fn, pred) if true_fn is not None else 0
    if false_fn is not None:
        notp = logical_not_layer(pred)
        run_branch(false_fn, notp)
    outs = [results[i] for i in sorted(results)]
    if len(outs) == 1:
        return outs[0]
    return outs


class IfElse:
    """Reference layers/control_flow.py:2193 — here a thin adapter over two
    conditional blocks with shared outputs."""

    OUT_IF_ELSE_BLOCKS = 2
    IN_IF_ELSE_TRUE_BLOCKS = 0
    IN_IF_ELSE_FALSE_BLOCKS = 1

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self._slots = []       # shared output vars (parent block)
        self._counts = {True: 0, False: 0}
        self._branch = None

    @contextlib.contextmanager
    def true_block(self):
        self._branch = True
        with _cond_block(self.helper, self.cond):
            yield
        self._branch = None

    @contextlib.contextmanager
    def false_block(self):
        self._branch = False
        notp = logical_not_layer(self.cond)
        with _cond_block(self.helper, notp):
            yield
        self._branch = None

    def input(self, x):
        return x

    def output(self, *outs):
        """Both branches assign into SHARED parent-block slot vars (by call
        position), so a concretely-skipped branch leaves the other branch's
        write in place and no merge op is needed; under a traced predicate
        the two lax.cond selections compose to a where."""
        if self._branch is None:
            raise ValueError("output() must be called inside a branch block")
        program = self.helper.main_program
        cur = program.current_block()
        parent = cur.parent_block
        base = self._counts[self._branch]
        for k, o in enumerate(outs):
            i = base + k
            if i >= len(self._slots):
                self._slots.append(parent.create_var(
                    name=unique_name.generate("ifelse_out"), dtype=o.dtype,
                    shape=o.shape,
                ))
            cur.append_op(type="assign", inputs={"X": [o]},
                          outputs={"Out": [self._slots[i].name]})
        self._counts[self._branch] = base + len(outs)

    def __call__(self):
        if self._counts[True] != self._counts[False] and \
                0 not in (self._counts[True], self._counts[False]):
            raise ValueError("true/false branches produced different arity")
        return list(self._slots)


class StaticRNN:
    """Fixed-length RNN over the time axis (reference
    layers/control_flow.py:417) lowered to one `recurrent` op = lax.scan.

    Usage::

        rnn = StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)          # x: [T, B, D] -> x_t: [B, D]
            h_prev = rnn.memory(init=h0)     # or shape/value init
            h = some_layers(x_t, h_prev)
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()                          # [T, B, H]
    """

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self.seq_inputs = []      # (outer var, inner var)
        self.memories = {}        # inner pre-state name -> (init var, new inner var)
        self.step_outputs = []    # inner vars
        self._block = None
        self.outputs = []

    @contextlib.contextmanager
    def step(self):
        program = self.helper.main_program
        self._parent = program.current_block()
        self.status = StaticRNN.IN_RNN_BLOCK
        with _sub_block(program) as blk:
            self._block = blk
            yield
        self.status = StaticRNN.AFTER_RNN_BLOCK
        self._complete()

    def _assert_in_rnn_block(self):
        if self.status != StaticRNN.IN_RNN_BLOCK:
            raise ValueError("must be called inside rnn.step()")

    def step_input(self, x):
        self._assert_in_rnn_block()
        inner = self._block.create_var(
            name=unique_name.generate("rnn_step_in"),
            dtype=x.dtype,
            shape=tuple(x.shape[1:]) if x.shape else None,
        )
        self.seq_inputs.append((x, inner))
        return inner

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1, dtype="float32"):
        self._assert_in_rnn_block()
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs `init` or (`shape`+`batch_ref`)")
            from . import tensor as ltensor

            # build init in the parent block
            program = self.helper.main_program
            cur = program.current_block_idx
            program.current_block_idx = self._parent.idx
            try:
                # reference API passes shape WITH the batch dim as -1
                # (layers/control_flow.py StaticRNN.memory); accept both
                full = list(shape) if shape and shape[0] == -1 \
                    else [-1] + list(shape)
                init = ltensor.fill_constant_batch_size_like(
                    input=batch_ref, shape=full,
                    value=init_value, dtype=dtype,
                    input_dim_idx=ref_batch_dim_idx, output_dim_idx=0,
                )
            finally:
                program.current_block_idx = cur
        inner = self._block.create_var(
            name=unique_name.generate("rnn_mem"),
            dtype=init.dtype, shape=init.shape,
        )
        self.memories[inner.name] = [init, None]
        return inner

    def update_memory(self, mem, var):
        self._assert_in_rnn_block()
        if mem.name not in self.memories:
            raise ValueError("%r is not a memory of this RNN" % mem.name)
        self.memories[mem.name][1] = var

    def step_output(self, o):
        self._assert_in_rnn_block()
        self.step_outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        blk = self._block
        parent = self._parent
        for name, (init, new) in self.memories.items():
            if new is None:
                raise ValueError("memory %r never updated" % name)
        special = set(i.name for _, i in self.seq_inputs) | set(self.memories)
        captured, _ = _collect_captures(blk, parent, skip=special)

        outer_outs = []
        for o in self.step_outputs:
            ov = parent.create_var(
                name=unique_name.generate("rnn_out"), dtype=o.dtype,
                shape=None,
            )
            outer_outs.append(ov)
        final_states = []
        state_names = [self.memories[k][1].name for k in self.memories]
        for k in self.memories:
            init, new = self.memories[k]
            fv = parent.create_var(
                name=unique_name.generate("rnn_final"), dtype=new.dtype,
                shape=new.shape,
            )
            final_states.append(fv)

        parent.append_op(
            type="recurrent",
            inputs={
                "StepInputs": [x.name for x, _ in self.seq_inputs],
                "Initials": [self.memories[k][0].name for k in self.memories],
                "Captured": captured,
            },
            outputs={
                "StepOutputs": [v.name for v in outer_outs],
                "FinalStates": [v.name for v in final_states],
            },
            attrs={
                "sub_block": blk.idx,
                "step_input_names": [i.name for _, i in self.seq_inputs],
                "pre_state_names": list(self.memories.keys()),
                "state_names": state_names,
                "step_output_names": [o.name for o in self.step_outputs],
                "captured_names": captured,
                "reverse": False,
            },
        )
        self.outputs = outer_outs

    def __call__(self, *args):
        if len(self.outputs) == 1:
            return self.outputs[0]
        return self.outputs
