"""Layer wrappers for the extended op surface (vision / detection /
losses / misc) — parity fills for python/paddle/fluid/layers/nn.py and
layers/detection.py entries not covered by the core modules."""

import numpy as np

from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    # vision
    "lrn", "affine_channel", "shuffle_channel", "space_to_depth",
    "temporal_shift", "grid_sampler", "affine_grid", "conv3d",
    "conv3d_transpose", "pool3d", "adaptive_pool3d", "row_conv",
    "bilinear_tensor_product", "spectral_norm", "data_norm", "fsp_matrix",
    # losses
    "bpr_loss", "rank_loss", "margin_rank_loss", "sigmoid_focal_loss",
    "teacher_student_sigmoid_loss", "mean_iou", "center_loss", "dice_loss",
    "warpctc", "edit_distance",
    # misc
    "multiplex", "crop", "crop_tensor", "pad_constant_like", "scatter_nd",
    "scatter_nd_add", "shard_index", "sampling_id", "random_crop",
    "unique_with_counts", "gather_tree", "add_position_encoding", "selu",
    "soft_relu", "rank", "size", "sum", "uniform_random", "expand_as",
    "logical_xor", "hard_swish", "autoincreased_step_counter",
    # detection
    "iou_similarity", "prior_box", "density_prior_box", "anchor_generator",
    "box_coder", "box_clip", "yolo_box", "bipartite_match", "target_assign",
    "multiclass_nms", "roi_align", "roi_pool",
    "linear_chain_crf", "crf_decoding",
    "nce", "hsigmoid", "py_func", "sync_batch_norm_layer", "Print",
]


def _simple(op_type, io=None, n_out=1, helper_name=None, attr_names=()):
    """Factory: layer fn appending one op; io maps python kwarg -> slot.
    Positional args beyond the input slots map to `attr_names` in order
    (the fluid convention: e.g. space_to_depth(x, blocksize))."""
    in_slots = io or {"x": "X"}

    def layer(*args, name=None, **kwargs):
        helper = LayerHelper(helper_name or op_type, name=name)
        inputs = {}
        pos = list(in_slots.items())
        for i, a in enumerate(args[:len(pos)]):
            if a is not None:
                inputs[pos[i][1]] = [a]
        for i, a in enumerate(args[len(pos):]):
            if i >= len(attr_names):
                raise TypeError(
                    "%s: too many positional arguments" % op_type)
            kwargs[attr_names[i]] = a
        for k, slot in in_slots.items():
            if k in kwargs and kwargs[k] is not None:
                inputs[slot] = [kwargs.pop(k)]
        ref = next(iter(inputs.values()))[0]
        outs = [helper.create_variable_for_type_inference(ref.dtype)
                for _ in range(n_out)]
        from ..core.registry import get_op_def

        opdef = get_op_def(op_type)
        helper.append_op(
            type=op_type, inputs=inputs,
            outputs={s: [o] for s, o in zip(opdef.output_slots, outs)},
            attrs=kwargs)
        return outs[0] if n_out == 1 else tuple(outs)

    layer.__name__ = helper_name or op_type
    return layer


# -- vision -------------------------------------------------------------------

lrn = _simple("lrn", attr_names=("n", "k", "alpha", "beta"))
affine_channel = _simple("affine_channel",
                         {"x": "X", "scale": "Scale", "bias": "Bias"}, 1)
shuffle_channel = _simple("shuffle_channel", attr_names=("group",))
space_to_depth = _simple("space_to_depth", attr_names=("blocksize",))
temporal_shift = _simple("temporal_shift",
                         attr_names=("seg_num", "shift_ratio"))
grid_sampler = _simple("grid_sampler", {"x": "X", "grid": "Grid"})
fsp_matrix = _simple("fsp", {"x": "X", "y": "Y"})


def affine_grid(theta, out_shape, name=None):
    helper = LayerHelper("affine_grid", name=name)
    out = helper.create_variable_for_type_inference(theta.dtype)
    inputs = {"Theta": [theta]}
    attrs = {}
    if isinstance(out_shape, Variable):
        inputs["OutputShape"] = [out_shape]
    else:
        attrs["output_shape"] = [int(v) for v in out_shape]
    helper.append_op(type="affine_grid", inputs=inputs,
                     outputs={"Output": [out]}, attrs=attrs)
    return out


def _conv3d_impl(op_type, input, num_filters, filter_size, stride, padding,
                 dilation, groups, param_attr, bias_attr, act, name,
                 transpose=False):
    helper = LayerHelper(op_type, bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1]
    groups = groups or 1
    fs = [filter_size] * 3 if isinstance(filter_size, int) else list(filter_size)
    st = [stride] * 3 if isinstance(stride, int) else list(stride)
    pd = [padding] * 3 if isinstance(padding, int) else list(padding)
    dl = [dilation] * 3 if isinstance(dilation, int) else list(dilation)
    if transpose:
        shape = [c, num_filters // groups] + fs
    else:
        shape = [num_filters, c // groups] + fs
    w = helper.create_parameter(attr=param_attr, shape=shape,
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type=op_type, inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": st, "paddings": pd, "dilations": dl,
               "groups": groups})
    pre = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    return _conv3d_impl("conv3d", input, num_filters, filter_size, stride,
                        padding, dilation, groups, param_attr, bias_attr,
                        act, name)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    return _conv3d_impl("conv3d_transpose", input, num_filters, filter_size,
                        stride, padding, dilation, groups, param_attr,
                        bias_attr, act, name, transpose=True)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCDHW"):
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ks = [pool_size] * 3 if isinstance(pool_size, int) else list(pool_size)
    st = [pool_stride] * 3 if isinstance(pool_stride, int) else list(pool_stride)
    pd = [pool_padding] * 3 if isinstance(pool_padding, int) else list(pool_padding)
    helper.append_op(
        type="pool3d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": ks, "strides": st,
               "paddings": pd, "global_pooling": global_pooling,
               "ceil_mode": ceil_mode, "exclusive": exclusive})
    return out


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    helper = LayerHelper("adaptive_pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ks = [pool_size] * 3 if isinstance(pool_size, int) else list(pool_size)
    helper.append_op(
        type="pool3d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": ks, "adaptive": True,
               "strides": [1, 1, 1], "paddings": [0, 0, 0]})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", act=act)
    w = helper.create_parameter(
        attr=param_attr, shape=[future_context_size + 1, input.shape[-1]],
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="row_conv",
                     inputs={"X": [input], "Filter": [w]},
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", bias_attr=bias_attr,
                         act=act, name=name)
    w = helper.create_parameter(
        attr=param_attr, shape=[size, x.shape[1], y.shape[1]],
        dtype=x.dtype)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr, shape=[size],
                                    dtype=x.dtype, is_bias=True)
        if b is not None:
            inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    helper = LayerHelper("spectral_norm", name=name)
    shape = weight.shape
    perm_dim = shape[dim]
    rest = 1
    for i, d in enumerate(shape):
        if i != dim:
            rest *= int(d)
    from ..initializer import Normal

    u = helper.create_parameter(attr=None, shape=[perm_dim],
                                dtype=weight.dtype,
                                default_initializer=Normal(0, 1))
    u.stop_gradient = True
    v = helper.create_parameter(attr=None, shape=[rest], dtype=weight.dtype,
                                default_initializer=Normal(0, 1))
    v.stop_gradient = True
    out = helper.create_variable_for_type_inference(weight.dtype)
    helper.append_op(
        type="spectral_norm",
        inputs={"Weight": [weight], "U": [u], "V": [v]},
        outputs={"Out": [out]},
        attrs={"dim": dim, "power_iters": power_iters, "eps": eps})
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, name=None,
              **kwargs):
    helper = LayerHelper("data_norm", act=act, name=name)
    c = input.shape[-1]
    from ..initializer import Constant

    bsize = helper.create_parameter(attr=None, shape=[c], dtype=input.dtype,
                                    default_initializer=Constant(1e4))
    bsum = helper.create_parameter(attr=None, shape=[c], dtype=input.dtype,
                                   default_initializer=Constant(0.0))
    bsq = helper.create_parameter(attr=None, shape=[c], dtype=input.dtype,
                                  default_initializer=Constant(1e4))
    for p in (bsize, bsum, bsq):
        p.stop_gradient = True
    out = helper.create_variable_for_type_inference(input.dtype)
    means = helper.create_variable_for_type_inference(input.dtype)
    scales = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="data_norm",
        inputs={"X": [input], "BatchSize": [bsize], "BatchSum": [bsum],
                "BatchSquareSum": [bsq]},
        outputs={"Y": [out], "Means": [means], "Scales": [scales]},
        attrs={"epsilon": epsilon})
    return helper.append_activation(out)


# -- losses -------------------------------------------------------------------

bpr_loss = _simple("bpr_loss", {"input": "X", "label": "Label"})
rank_loss = _simple("rank_loss",
                    {"label": "Label", "left": "Left", "right": "Right"})


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(
        type="margin_rank_loss",
        inputs={"Label": [label], "X1": [left], "X2": [right]},
        outputs={"Out": [out], "Activated": [act]},
        attrs={"margin": margin})
    return out


def sigmoid_focal_loss(x, label, fg_num, gamma=2, alpha=0.25):
    helper = LayerHelper("sigmoid_focal_loss")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sigmoid_focal_loss",
        inputs={"X": [x], "Label": [label], "FgNum": [fg_num]},
        outputs={"Out": [out]},
        attrs={"gamma": float(gamma), "alpha": float(alpha)})
    return out


teacher_student_sigmoid_loss = _simple(
    "teacher_student_sigmoid_loss", {"input": "X", "label": "Label"})


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int32")
    correct = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="mean_iou",
        inputs={"Predictions": [input], "Labels": [label]},
        outputs={"OutMeanIou": [miou], "OutWrong": [wrong],
                 "OutCorrect": [correct]},
        attrs={"num_classes": num_classes})
    return miou, wrong, correct


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    helper = LayerHelper("center_loss")
    from ..initializer import Constant

    centers = helper.create_parameter(
        attr=param_attr, shape=[num_classes, input.shape[1]],
        dtype=input.dtype, default_initializer=Constant(0.0))
    centers.stop_gradient = True
    rate = helper.create_or_get_global_variable(
        name=helper.name + ".rate", shape=[1], dtype="float32",
        persistable=True)
    Constant(float(alpha))(rate)
    rate.stop_gradient = True
    loss = helper.create_variable_for_type_inference(input.dtype)
    diff = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="center_loss",
        inputs={"X": [input], "Label": [label], "Centers": [centers],
                "CenterUpdateRate": [rate]},
        outputs={"CentersOut": [centers], "SampleCenterDiff": [diff],
                 "Loss": [loss]},
        attrs={"cluster_num": num_classes, "need_update": update_center})
    return loss


def dice_loss(input, label, epsilon=1e-5):
    """Pure composition (reference layers/nn.py dice_loss)."""
    from . import nn as L
    from . import tensor as T

    label = L.one_hot(label, input.shape[-1])
    reduce_dims = list(range(1, len(input.shape)))
    inse = L.reduce_sum(input * label, dim=reduce_dims)
    dice_denominator = L.reduce_sum(input, dim=reduce_dims) + L.reduce_sum(
        label, dim=reduce_dims)
    dice_score = 1 - inse * 2 / (dice_denominator + epsilon)
    return L.reduce_mean(dice_score)


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference(input.dtype)
    grad = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="warpctc", inputs={"Logits": [input], "Label": [label]},
        outputs={"WarpCTCGrad": [grad], "Loss": [loss]},
        attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    helper = LayerHelper("edit_distance")
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="edit_distance", inputs={"Hyps": [input], "Refs": [label]},
        outputs={"Out": [out], "SequenceNum": [seq_num]},
        attrs={"normalized": normalized})
    return out, seq_num


# -- misc ---------------------------------------------------------------------


def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(type="multiplex",
                     inputs={"Ids": [index], "X": list(inputs)},
                     outputs={"Out": [out]})
    return out


def crop_tensor(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop_tensor", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="crop_tensor", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"shape": list(shape or []), "offsets": list(offsets or [])})
    return out


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    attrs = {"offsets": list(offsets or [])}
    if isinstance(shape, Variable):
        inputs["Y"] = [shape]
    else:
        attrs["shape"] = list(shape or [])
    helper.append_op(type="crop", inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs)
    return out


pad_constant_like = _simple("pad_constant_like", {"x": "X", "y": "Y"})
scatter_nd_add = _simple(
    "scatter_nd_add", {"ref": "X", "index": "Index", "updates": "Updates"})


def scatter_nd(index, updates, shape, name=None):
    helper = LayerHelper("scatter_nd", name=name)
    out = helper.create_variable_for_type_inference(updates.dtype)
    helper.append_op(
        type="scatter_nd",
        inputs={"Index": [index], "Updates": [updates]},
        outputs={"Out": [out]},
        attrs={"shape": [int(v) for v in shape]})
    return out


shard_index = _simple("shard_index", attr_names=(
    "index_num", "nshards", "shard_id", "ignore_value"))
sampling_id = _simple("sampling_id", attr_names=("min", "max", "seed"))
gather_tree = _simple("gather_tree", {"ids": "Ids", "parents": "Parents"})
add_position_encoding = _simple("add_position_encoding")
selu = _simple("selu")
soft_relu = _simple("soft_relu")


def random_crop(x, shape=None, seed=None):
    helper = LayerHelper("random_crop")
    out = helper.create_variable_for_type_inference(x.dtype)
    seed_out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="random_crop", inputs={"X": [x]},
        outputs={"Out": [out], "SeedOut": [seed_out]},
        attrs={"shape": [int(v) for v in (shape or [])]})
    return out


def unique_with_counts(x, dtype="int32"):
    helper = LayerHelper("unique_with_counts")
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(dtype)
    count = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="unique_with_counts", inputs={"X": [x]},
        outputs={"Out": [out], "Index": [index], "Count": [count]})
    return out, index, count


def rank(input):
    from . import tensor as T

    return T.fill_constant([1], "int32", len(input.shape))


def size(input):
    from . import tensor as T

    n = 1
    for d in input.shape:
        n *= int(d)
    return T.fill_constant([1], "int64", n)


def sum(x):
    helper = LayerHelper("sum")
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op(type="sum", inputs={"X": list(xs)},
                     outputs={"Out": [out]})
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,
                   name=None):
    helper = LayerHelper("uniform_random", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    from ..ops.common import dtype_enum

    helper.append_op(
        type="uniform_random", inputs={}, outputs={"Out": [out]},
        attrs={"shape": [int(v) for v in shape], "min": float(min),
               "max": float(max), "seed": seed, "dtype": dtype_enum(dtype)})
    return out


def expand_as(x, target_tensor, name=None):
    helper = LayerHelper("expand_as", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="expand_as", inputs={"X": [x], "target_tensor": [target_tensor]},
        outputs={"Out": [out]})
    return out


def logical_xor(x, y, out=None, name=None):
    helper = LayerHelper("logical_xor", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="logical_xor", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    helper = LayerHelper("hard_swish", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="hard_swish", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"threshold": threshold, "scale": scale, "offset": offset})
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistable step counter incremented each run
    (layers/nn.py autoincreased_step_counter)."""
    from ..initializer import Constant

    helper = LayerHelper("global_step_counter")
    counter = helper.create_or_get_global_variable(
        name=counter_name or "@STEP_COUNTER@", shape=[1], dtype="int64",
        persistable=True)
    if not getattr(counter, "_step_init", False):
        Constant(float(begin - step))(counter)
        counter._step_init = True
    counter.stop_gradient = True
    helper.append_op(type="increment", inputs={"X": [counter]},
                     outputs={"Out": [counter]}, attrs={"step": float(step)})
    return counter


# -- detection ----------------------------------------------------------------

iou_similarity = _simple("iou_similarity", {"x": "X", "y": "Y"})


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="prior_box", inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={"min_sizes": [float(v) for v in min_sizes],
               "max_sizes": [float(v) for v in (max_sizes or [])],
               "aspect_ratios": [float(v) for v in aspect_ratios],
               "variances": [float(v) for v in variance],
               "flip": flip, "clip": clip,
               "step_w": float(steps[0]), "step_h": float(steps[1]),
               "offset": offset,
               "min_max_aspect_ratios_order": min_max_aspect_ratios_order})
    return boxes, var


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    helper = LayerHelper("density_prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={"densities": [int(v) for v in (densities or [])],
               "fixed_sizes": [float(v) for v in (fixed_sizes or [])],
               "fixed_ratios": [float(v) for v in (fixed_ratios or [])],
               "variances": [float(v) for v in variance], "clip": clip,
               "step_w": float(steps[0]), "step_h": float(steps[1]),
               "offset": offset, "flatten_to_2d": flatten_to_2d})
    return boxes, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [var]},
        attrs={"anchor_sizes": [float(v) for v in (anchor_sizes or [64.0])],
               "aspect_ratios": [float(v) for v in (aspect_ratios or [1.0])],
               "variances": [float(v) for v in variance],
               "stride": [float(v) for v in (stride or [16.0, 16.0])],
               "offset": offset})
    return anchors, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None,
              axis=0):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    attrs = {"code_type": code_type, "box_normalized": box_normalized,
             "axis": axis}
    if isinstance(prior_box_var, Variable):
        inputs["PriorBoxVar"] = [prior_box_var]
    elif prior_box_var is not None:
        attrs["variance"] = [float(v) for v in prior_box_var]
    helper.append_op(type="box_coder", inputs=inputs,
                     outputs={"OutputBox": [out]}, attrs=attrs)
    return out


box_clip = _simple("box_clip", {"input": "Input", "im_info": "ImInfo"})


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype)
    scores = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="yolo_box", inputs={"X": [x], "ImgSize": [img_size]},
        outputs={"Boxes": [boxes], "Scores": [scores]},
        attrs={"anchors": [int(v) for v in anchors], "class_num": class_num,
               "conf_thresh": conf_thresh,
               "downsample_ratio": downsample_ratio, "clip_bbox": clip_bbox})
    return boxes, scores


def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=0.5,
                    name=None):
    helper = LayerHelper("bipartite_match", name=name)
    idx = helper.create_variable_for_type_inference("int32")
    d = helper.create_variable_for_type_inference(dist_matrix.dtype)
    helper.append_op(
        type="bipartite_match", inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [idx], "ColToRowMatchDist": [d]},
        attrs={"match_type": match_type, "dist_threshold": dist_threshold})
    return idx, d


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    w = helper.create_variable_for_type_inference("float32")
    inputs = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices]
    helper.append_op(type="target_assign", inputs=inputs,
                     outputs={"Out": [out], "OutWeight": [w]},
                     attrs={"mismatch_value": mismatch_value})
    return out, w


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    helper.append_op(
        type="multiclass_nms", inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out]},
        attrs={"background_label": background_label,
               "score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "nms_threshold": nms_threshold, "nms_eta": nms_eta,
               "keep_top_k": keep_top_k, "normalized": normalized})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None):
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="roi_align", inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
               "spatial_scale": spatial_scale,
               "sampling_ratio": sampling_ratio})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, name=None):
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    argmax = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="roi_pool", inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out], "Argmax": [argmax]},
        attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
               "spatial_scale": spatial_scale})
    return out


# -- CRF ----------------------------------------------------------------------


def linear_chain_crf(input, label, param_attr=None, length=None):
    """Linear-chain CRF NLL (reference layers/nn.py linear_chain_crf).
    input [B, T, C] dense emissions (LoD replaced by `length`)."""
    helper = LayerHelper("linear_chain_crf")
    num_tags = input.shape[-1]
    transition = helper.create_parameter(
        attr=param_attr, shape=[num_tags + 2, num_tags], dtype=input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype)
    eexp = helper.create_variable_for_type_inference(input.dtype)
    texp = helper.create_variable_for_type_inference(input.dtype)
    ll = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Emission": [input], "Transition": [transition],
              "Label": [label]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="linear_chain_crf", inputs=inputs,
        outputs={"Alpha": [alpha], "EmissionExps": [eexp],
                 "TransitionExps": [texp], "LogLikelihood": [ll]})
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    helper = LayerHelper("crf_decoding")
    if isinstance(param_attr, Variable):
        transition = param_attr
    elif getattr(param_attr, "name", None):
        transition = helper.main_program.global_block().var(param_attr.name)
    else:
        raise ValueError(
            "crf_decoding: param_attr must be the CRF transition Variable "
            "or a ParamAttr naming it (the parameter created by "
            "linear_chain_crf)")
    out = helper.create_variable_for_type_inference("int64")
    inputs = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        inputs["Label"] = [label]
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(type="crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [out]})
    return out


# -- sampled classifiers + host callback -------------------------------------


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise contrastive estimation (reference layers/nn.py nce).
    Samplers: "uniform", "log_uniform" (Zipfian), "custom_dist" (pass the
    per-class probabilities via `custom_dist`)."""
    sampler_ids = {"uniform": 0, "log_uniform": 1, "custom_dist": 2}
    if sampler not in sampler_ids:
        raise ValueError("nce: unknown sampler %r (have %s)"
                         % (sampler, sorted(sampler_ids)))
    if sampler == "custom_dist" and custom_dist is None:
        raise ValueError("nce: sampler='custom_dist' needs custom_dist")
    helper = LayerHelper("nce", name=name)
    dim = input.shape[1]
    w = helper.create_parameter(attr=param_attr,
                                shape=[num_total_classes, dim],
                                dtype=input.dtype)
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if custom_dist is not None:
        from . import tensor as _T

        probs = _T.assign(np.asarray(custom_dist, "float32"))
        probs.stop_gradient = True
        inputs["CustomDistProbs"] = [probs]
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr,
                                    shape=[num_total_classes],
                                    dtype=input.dtype, is_bias=True)
        if b is not None:
            inputs["Bias"] = [b]
    cost = helper.create_variable_for_type_inference(input.dtype)
    sl = helper.create_variable_for_type_inference(input.dtype)
    slab = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="nce", inputs=inputs,
        outputs={"Cost": [cost], "SampleLogits": [sl], "SampleLabels": [slab]},
        attrs={"num_total_classes": num_total_classes,
               "num_neg_samples": num_neg_samples or 10, "seed": seed,
               "sampler": sampler_ids[sampler], "is_sparse": is_sparse})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """Hierarchical sigmoid (reference layers/nn.py hsigmoid, SimpleCode
    complete-binary-tree mode)."""
    helper = LayerHelper("hierarchical_sigmoid", name=name)
    dim = input.shape[1]
    w = helper.create_parameter(attr=param_attr,
                                shape=[num_classes, dim], dtype=input.dtype)
    inputs = {"X": [input], "W": [w], "Label": [label]}
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr, shape=[num_classes],
                                    dtype=input.dtype, is_bias=True)
        if b is not None:
            inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    pre = helper.create_variable_for_type_inference(input.dtype)
    wout = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="hierarchical_sigmoid", inputs=inputs,
        outputs={"Out": [out], "PreOut": [pre], "W_Out": [wout]},
        attrs={"num_classes": num_classes, "is_sparse": is_sparse})
    return out


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host Python callback (reference layers/nn.py py_func): `out` is a
    pre-created Variable (or list) fixing shapes/dtypes.  backward_func is
    not supported on the XLA path (forward-only host op)."""
    from ..ops.sampled import register_py_func

    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        if any(int(d) < 0 for d in (o.shape or [])):
            raise ValueError(
                "py_func: out variable %r has a dynamic dim %s — pre-create "
                "it with a concrete shape (XLA host callbacks need static "
                "result shapes)" % (o.name, tuple(o.shape)))
    fid = register_py_func(func)
    helper.append_op(
        type="py_func", inputs={"X": list(xs)},
        outputs={"Out": list(outs)},
        attrs={"forward_callable_id": fid, "backward_callable_id": -1,
               "out_shapes": [list(o.shape) for o in outs],
               "out_dtypes": [str(o.dtype) for o in outs]})
    return out


def sync_batch_norm_layer(input, act=None, is_test=False, momentum=0.9,
                          epsilon=1e-5, param_attr=None, bias_attr=None,
                          data_layout="NCHW", moving_mean_name=None,
                          moving_variance_name=None, name=None):
    """layers-style sync BN builder (the dygraph-era paddle exposes this as
    paddle.nn.SyncBatchNorm; in fluid it is batch_norm + build-strategy
    sync_batch_norm=True — here the op is explicit)."""
    from ..initializer import Constant

    helper = LayerHelper("sync_batch_norm", act=act, name=name)
    dtype = input.dtype
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale_p = helper.create_parameter(attr=param_attr, shape=[c], dtype=dtype,
                                      default_initializer=Constant(1.0))
    bias_p = helper.create_parameter(attr=bias_attr, shape=[c], dtype=dtype,
                                     is_bias=True,
                                     default_initializer=Constant(0.0))
    mean = helper.create_or_get_global_variable(
        name=moving_mean_name or helper.name + ".mean", shape=[c],
        dtype=dtype, persistable=True)
    var = helper.create_or_get_global_variable(
        name=moving_variance_name or helper.name + ".var", shape=[c],
        dtype=dtype, persistable=True)
    mean.stop_gradient = var.stop_gradient = True
    if not getattr(mean, "_bn_initialized", False):
        Constant(0.0)(mean)
        Constant(1.0)(var)
        mean._bn_initialized = True
    sm = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    sv = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    rs = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="sync_batch_norm",
        inputs={"X": [input], "Scale": [scale_p], "Bias": [bias_p],
                "Mean": [mean], "Variance": [var]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [var],
                 "SavedMean": [sm], "SavedVariance": [sv],
                 "ReserveSpace": [rs]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout})
    return helper.append_activation(out)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Runtime tensor print (reference layers/control_flow.py Print)."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="print", inputs={"In": [input]}, outputs={"Out": [out]},
        attrs={"first_n": first_n, "message": message or "",
               "summarize": summarize,
               "print_tensor_name": print_tensor_name,
               "print_tensor_shape": print_tensor_shape})
    return out
