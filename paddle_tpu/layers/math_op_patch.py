"""Operator overloading on Variable (parity: layers/math_op_patch.py)."""

from ..framework import Variable
from ..layer_helper import LayerHelper

_supported = [
    ("__add__", "elementwise_add", False),
    ("__radd__", "elementwise_add", True),
    ("__sub__", "elementwise_sub", False),
    ("__rsub__", "elementwise_sub", True),
    ("__mul__", "elementwise_mul", False),
    ("__rmul__", "elementwise_mul", True),
    ("__truediv__", "elementwise_div", False),
    ("__rtruediv__", "elementwise_div", True),
    ("__pow__", "elementwise_pow", False),
    ("__mod__", "elementwise_mod", False),
    ("__floordiv__", "elementwise_floordiv", False),
    ("__lt__", "less_than", False),
    ("__le__", "less_equal", False),
    ("__gt__", "greater_than", False),
    ("__ge__", "greater_equal", False),
]


def _scalar_to_var(val, ref):
    from . import tensor

    return tensor.fill_constant([1], ref.dtype, float(val))


def _binary(op_type, reverse):
    def impl(self, other):
        if not isinstance(other, Variable):
            if isinstance(other, (int, float)):
                # scalar fast path via scale op for add/sub/mul/div
                if op_type == "elementwise_add" and not reverse:
                    from .nn import scale

                    return scale(self, scale=1.0, bias=float(other))
                if op_type == "elementwise_mul":
                    from .nn import scale

                    return scale(self, scale=float(other))
                other = _scalar_to_var(other, self)
            else:
                return NotImplemented
        x, y = (other, self) if reverse else (self, other)
        helper = LayerHelper(op_type)
        is_cmp = op_type in ("less_than", "less_equal", "greater_than",
                             "greater_equal", "equal", "not_equal")
        out = helper.create_variable_for_type_inference(
            dtype="bool" if is_cmp else x.dtype
        )
        helper.append_op(
            type=op_type,
            inputs={"X": [x], "Y": [y]},
            outputs={"Out": [out]},
            attrs={} if is_cmp else {"axis": -1},
        )
        return out

    return impl


def _neg(self):
    from .nn import scale

    return scale(self, scale=-1.0)


def _eq(self, other):
    # keep identity semantics for dict/set usage; layers.equal exists for
    # elementwise compare
    return self is other


def monkey_patch_variable():
    for name, op_type, rev in _supported:
        setattr(Variable, name, _binary(op_type, rev))
    Variable.__neg__ = _neg


monkey_patch_variable()
