"""Final API-surface fills: DynamicRNN, load, reorder_lod_tensor_by_rank,
and the layer-codegen/doc helpers (parity: layers/control_flow.py DynamicRNN,
layers/io.py:884 load, layers/control_flow.py reorder_lod_tensor_by_rank,
layer_function_generator.py)."""

import contextlib
import functools
import warnings

from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "DynamicRNN", "load", "reorder_lod_tensor_by_rank", "lod_rank_table",
    "autodoc", "templatedoc", "deprecated", "generate_layer_fn",
    "generate_activation_fn",
]


# -- codegen/doc helpers (layer_function_generator.py) ------------------------


def autodoc(comment=""):
    """Decorator stamping a generated docstring (reference
    layer_function_generator.py autodoc)."""

    def deco(func):
        func.__doc__ = comment + (func.__doc__ or "")
        return func

    return deco


def templatedoc(op_type=None):
    """Decorator filling ${comment}-style slots from the op's registered
    metadata; our registry has no OpProto comments, so the template slots
    are stripped (API-compatible no-op)."""

    def deco(func):
        doc = func.__doc__ or ""
        func.__doc__ = doc.replace("${comment}", "").strip()
        return func

    return deco


def deprecated(since, instead, extra_message=""):
    """Decorator emitting a DeprecationWarning (reference deprecated.py)."""

    def deco(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(
                "API %r is deprecated since %s, use %s instead. %s"
                % (func.__name__, since, instead, extra_message),
                DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        return wrapper

    return deco


def generate_layer_fn(op_type):
    """Build a layer function from a registered op (reference
    layer_function_generator.py:generate_layer_fn): inputs map positionally
    to the op's input slots, keywords to attrs, one output."""
    from ..core.registry import get_op_def

    opdef = get_op_def(op_type)

    def layer(*args, **kwargs):
        helper = LayerHelper(op_type, name=kwargs.pop("name", None))
        inputs = {}
        for slot, a in zip(opdef.input_slots, args):
            if a is not None:
                inputs[slot] = a if isinstance(a, (list, tuple)) else [a]
        for slot in opdef.input_slots:
            if slot in kwargs:
                v = kwargs.pop(slot)
            elif slot.lower() in kwargs and isinstance(
                    kwargs[slot.lower()], Variable):
                # only claim the lowercase spelling when it is a Variable —
                # attrs may share the name (e.g. a "shape" attr vs "Shape"
                # input slot)
                v = kwargs.pop(slot.lower())
            else:
                continue
            if v is not None and slot not in inputs:
                inputs[slot] = v if isinstance(v, (list, tuple)) else [v]
        ref = next(iter(inputs.values()))[0] if inputs else None
        dtype = kwargs.pop("dtype", None) or (
            ref.dtype if ref is not None else "float32")
        outs = [helper.create_variable_for_type_inference(dtype)
                for _ in opdef.output_slots]
        helper.append_op(
            type=op_type, inputs=inputs,
            outputs={s: [o] for s, o in zip(opdef.output_slots, outs)},
            attrs=kwargs)
        return outs[0] if len(outs) == 1 else tuple(outs)

    layer.__name__ = op_type
    return layer


def generate_activation_fn(op_type):
    """One-input one-output activation layer from the registry (reference
    layer_function_generator.py:generate_activation_fn)."""

    def layer(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]})
        return out

    layer.__name__ = op_type
    return layer


# -- load / reorder -----------------------------------------------------------


def load(out, file_path, load_as_fp16=None):
    """Load a tensor saved by the `save` op into `out` (layers/io.py:884)."""
    helper = LayerHelper("load")
    attrs = {"file_path": file_path}
    if load_as_fp16 is not None:
        attrs["load_as_fp16"] = load_as_fp16
    helper.append_op(type="load", inputs={}, outputs={"Out": [out]},
                     attrs=attrs)


def lod_rank_table(x, level=0, seq_len=None):
    """Rank table: batch indices sorted by sequence length descending
    (reference control_flow.py lod_rank_table).  Padded design: lengths come
    from `seq_len` [B]; without it every row ranks equally (identity)."""
    from . import nn as _nn
    from . import tensor as _tensor

    if seq_len is None:
        raise ValueError(
            "lod_rank_table needs seq_len in the padded-batch design "
            "(the reference reads it from the LoD)")
    neg = _tensor.cast(seq_len, "float32") * -1.0
    _, idx = _nn.argsort(neg, axis=0)
    return idx


def reorder_lod_tensor_by_rank(x, rank_table):
    """Permute batch rows by a rank table (reference
    reorder_lod_tensor_by_rank_op.cc); rank_table is the index tensor
    produced by lod_rank_table."""
    from . import nn as _nn

    return _nn.gather(x, rank_table)


# -- DynamicRNN ---------------------------------------------------------------


class DynamicRNN:
    """Variable-length RNN (reference layers/control_flow.py DynamicRNN).

    Padded-batch design: the reference sorts sequences by length and shrinks
    the active batch each step; here every step runs the full padded batch
    and a per-step mask freezes memories of finished rows (identical math,
    XLA-friendly static shapes).

    Usage::

        drnn = DynamicRNN()
        with drnn.block():
            x_t = drnn.step_input(x, seq_len=lens)   # x: [B, T, D]
            h = drnn.memory(shape=[H], value=0.0)
            nh = fluid.layers.fc(x_t, H) + h
            drnn.update_memory(h, nh)
            drnn.output(nh)
        out, = drnn()                                # [B, T, H] zero-padded
    """

    def __init__(self, name=None):
        from .control_flow import StaticRNN

        self._rnn = StaticRNN(name=name)
        self._in_block = False
        self._mask = None          # inner [B, 1] mask for this step
        self._seq_len = None
        self._outer_inputs = []    # original [B, T, ...] vars
        self._outputs = []

    @contextlib.contextmanager
    def block(self):
        with self._rnn.step():
            self._in_block = True
            yield
            self._in_block = False

    @contextlib.contextmanager
    def _parent_block(self):
        """Build ops in the RNN's parent block (the scan's outer inputs must
        be produced outside the sub-block, cf. StaticRNN.memory)."""
        program = self._rnn.helper.main_program
        cur = program.current_block_idx
        program.current_block_idx = self._rnn._parent.idx
        try:
            yield
        finally:
            program.current_block_idx = cur

    def step_input(self, x, level=0, seq_len=None):
        """x [B, T, ...] batch-major (the reference takes a LoD tensor);
        `seq_len` [B] activates masking (first call wins)."""
        from . import nn as _nn

        if not self._in_block:
            raise ValueError("step_input must be called inside block()")
        # StaticRNN scans time-major
        perm = [1, 0] + list(range(2, len(x.shape)))
        with self._parent_block():
            xt = _nn.transpose(x, perm)
        inner = self._rnn.step_input(xt)
        if seq_len is not None and self._mask is None:
            self._seq_len = seq_len
            T = x.shape[1]
            from .sequence_lod import sequence_mask

            with self._parent_block():
                m = sequence_mask(seq_len, maxlen=T, dtype=x.dtype)  # [B, T]
                mt = _nn.transpose(m, [1, 0])                        # [T, B]
                mt = _nn.reshape(mt, [T, -1, 1])                     # [T, B, 1]
            self._mask = self._rnn.step_input(mt)                    # [B, 1]
        self._outer_inputs.append(x)
        return inner

    def static_input(self, x):
        """Non-stepped input; captured automatically by the scan body."""
        return x

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype="float32"):
        if not self._in_block:
            raise ValueError("memory must be called inside block()")
        if init is not None:
            return self._rnn.memory(init=init)
        if not self._outer_inputs:
            raise ValueError("call step_input before memory(shape=...)")
        return self._rnn.memory(shape=list(shape), batch_ref=self._outer_inputs[0],
                                init_value=value, ref_batch_dim_idx=0,
                                dtype=dtype)

    def _apply_mask(self, x, mask):
        """x*mask broadcasting [B,1] over [B,...] (fluid axis=0 semantics)."""
        from . import nn as _nn

        return _nn.elementwise_mul(x, mask, axis=0)

    def update_memory(self, ex_mem, new_mem):
        if self._mask is not None:
            # freeze finished rows: new = new*m + old*(1-m)
            new_mem = self._apply_mask(new_mem, self._mask) + \
                self._apply_mask(ex_mem, 1.0 - self._mask)
        self._rnn.update_memory(ex_mem, new_mem)

    def output(self, *outputs):
        for o in outputs:
            if self._mask is not None:
                o = self._apply_mask(o, self._mask)
            self._rnn.step_output(o)
            self._outputs.append(o)

    def __call__(self):
        from . import nn as _nn

        rnn_outs = self._rnn()
        if not isinstance(rnn_outs, (list, tuple)):
            rnn_outs = [rnn_outs]
        outs = []
        for ov in rnn_outs:
            # back to batch-major [B, T, ...]
            perm = [1, 0] + list(range(2, len(ov.shape or (0, 0))))
            outs.append(_nn.transpose(ov, perm))
        return outs
