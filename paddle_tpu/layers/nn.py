"""Op-builder layer API (parity: python/paddle/fluid/layers/nn.py, ~200 fns).

Each function appends ops to the current block and returns output Variables.
"""

from .. import flags as _flags
from ..framework import Variable, convert_np_dtype_to_dtype_
from ..layer_helper import LayerHelper
from ..ops.common import dtype_enum

__all__ = [
    "fc",
    "embedding",
    "flash_attention",
    "ring_attention",
    "dropout",
    "softmax",
    "log_softmax",
    "cross_entropy",
    "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "square_error_cost",
    "accuracy",
    "auc",
    "topk",
    "matmul",
    "mul",
    "conv2d",
    "conv2d_bn_relu",
    "conv2d_transpose",
    "pool2d",
    "adaptive_pool2d",
    "batch_norm",
    "layer_norm",
    "fused_dropout_add_ln",
    "group_norm",
    "instance_norm",
    "relu",
    "label_smooth",
    "mean",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "reduce_all",
    "reduce_any",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "elementwise_mod",
    "elementwise_floordiv",
    "clip",
    "clip_by_norm",
    "l2_normalize",
    "scale",
    "sums",
    "transpose",
    "reshape",
    "squeeze",
    "unsqueeze",
    "flatten",
    "concat",
    "split",
    "stack",
    "unstack",
    "expand",
    "slice",
    "strided_slice",
    "gather",
    "gather_nd",
    "scatter",
    "one_hot",
    "pad",
    "pad2d",
    "lod_reset",
    "shape",
    "argmax",
    "argmin",
    "argsort",
    "where",
    "gelu",
    "leaky_relu",
    "prelu",
    "elu",
    "relu6",
    "pow",
    "hard_sigmoid",
    "swish",
    "image_resize",
    "resize_bilinear",
    "resize_nearest",
    "cos_sim",
    "smooth_l1",
    "huber_loss",
    "kldiv_loss",
    "log_loss",
    "mse_loss",
    "npair_loss",
    "uniform_random_batch_size_like",
    "gaussian_random",
    "sampled_softmax_with_cross_entropy",
    "unfold",
    "pixel_shuffle",
]


def _single_out_layer(op_type, helper_name=None, x_slot="X", out_slot="Out"):
    """Build a layers.* function for a single-in single-out op."""

    def layer(x, *args, name=None, **attrs):
        helper = LayerHelper(helper_name or op_type, name=name)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(
            type=op_type,
            inputs={x_slot: [x]},
            outputs={out_slot: [out]},
            attrs=attrs,
        )
        return out

    layer.__name__ = helper_name or op_type
    return layer


# -- dense / matmul ----------------------------------------------------------


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None):
    """Fully-connected layer (reference layers/nn.py:fc): mul per input +
    sum + bias + act."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = param_attr if isinstance(param_attr, (list, tuple)) else [
        param_attr
    ] * len(inputs)
    mul_results = []
    for inp, pattr in zip(inputs, param_attrs):
        input_shape = inp.shape
        in_features = 1
        for d in input_shape[num_flatten_dims:]:
            in_features *= int(d)
        w = helper.create_parameter(
            attr=pattr, shape=[in_features, size], dtype=dtype
        )
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="mul",
            inputs={"X": [inp], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="sum",
            inputs={"X": mul_results},
            outputs={"Out": [pre_bias]},
        )
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32", name=None):
    helper = LayerHelper("embedding", name=name)
    w = helper.create_parameter(attr=param_attr, shape=list(size), dtype=dtype)
    if is_distributed and not w.sharding:
        # TPU-native equivalent of the reference's pserver-sharded table
        # (distributed_lookup_table_op + parameter_prefetch): row-shard the
        # table over the mesh "model" axis; under pjit XLA inserts the
        # gather collectives over ICI.  On meshes without a "model" axis the
        # annotation is dropped (table replicated).
        w.sharding = ("model", None)
    out = helper.create_variable_for_type_inference(dtype)
    pad = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx
    )
    helper.append_op(
        type="lookup_table",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [out]},
        attrs={"is_sparse": is_sparse, "is_distributed": is_distributed,
               "padding_idx": pad},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y,
               "alpha": float(alpha)},
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="mul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


# -- losses ------------------------------------------------------------------


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax = helper.create_variable_for_type_inference(dtype=logits.dtype)
    loss = helper.create_variable_for_type_inference(dtype=logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax], "Loss": [loss]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index,
               "numeric_stable_mode": numeric_stable_mode, "axis": axis},
    )
    if return_softmax:
        return loss, softmax
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      normalize=False, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    diff = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="elementwise_sub",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [diff]},
    )
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="square", inputs={"X": [diff]}, outputs={"Out": [out]}
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1")
    diff = helper.create_variable_for_type_inference(dtype=x.dtype)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(
        type="smooth_l1_loss",
        inputs=inputs,
        outputs={"Diff": [diff], "Out": [out]},
        attrs={"sigma": sigma if sigma is not None else 1.0},
    )
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    residual = helper.create_variable_for_type_inference(dtype=input.dtype)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="huber_loss",
        inputs={"X": [input], "Y": [label]},
        outputs={"Residual": [residual], "Out": [out]},
        attrs={"delta": delta},
    )
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="kldiv_loss",
        inputs={"X": [x], "Target": [target]},
        outputs={"Loss": [out]},
        attrs={"reduction": reduction},
    )
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="log_loss",
        inputs={"Predicted": [input], "Labels": [label]},
        outputs={"Loss": [out]},
        attrs={"epsilon": epsilon},
    )
    return out


def mse_loss(input, label):
    return reduce_mean(square_error_cost(input, label))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair loss (reference layers/loss.py) composed from primitives."""
    from . import tensor as ltensor

    l2loss = reduce_mean(reduce_sum(elementwise_mul(anchor, anchor), dim=[1]))
    l2loss = elementwise_add(
        l2loss,
        reduce_mean(reduce_sum(elementwise_mul(positive, positive), dim=[1]))
    )
    l2loss = scale(l2loss, scale=l2_reg * 0.25)
    similarity = matmul(anchor, positive, transpose_y=True)
    softlab = softmax(similarity)
    xent = cross_entropy(softlab, labels, soft_label=True)
    return elementwise_add(reduce_mean(xent), l2loss)


def sampled_softmax_with_cross_entropy(logits, label, num_samples, **kwargs):
    # TPU: dense softmax is MXU-fast; sampling is rarely a win — full softmax
    return softmax_with_cross_entropy(logits, label)


# -- metrics -----------------------------------------------------------------


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    topk_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    topk_indices = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [topk_out], "Indices": [topk_indices]},
        attrs={"k": k},
    )
    acc_out = helper.create_variable_for_type_inference(dtype="float32")
    if correct is None:
        correct = helper.create_variable_for_type_inference(dtype="int32")
    if total is None:
        total = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices],
                "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct],
                 "Total": [total]},
    )
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    helper = LayerHelper("auc")
    stat_pos = helper.create_or_get_global_variable(
        name=helper.name + "_stat_pos", shape=[num_thresholds + 1],
        dtype="int64", persistable=True
    )
    stat_neg = helper.create_or_get_global_variable(
        name=helper.name + "_stat_neg", shape=[num_thresholds + 1],
        dtype="int64", persistable=True
    )
    from ..initializer import Constant

    for v in (stat_pos, stat_neg):
        Constant(0)(v)
    auc_out = helper.create_variable_for_type_inference(dtype="float64")
    helper.append_op(
        type="auc",
        inputs={"Predict": [input], "Label": [label],
                "StatPos": [stat_pos], "StatNeg": [stat_neg]},
        outputs={"AUC": [auc_out], "StatPosOut": [stat_pos],
                 "StatNegOut": [stat_neg]},
        attrs={"curve": curve, "num_thresholds": num_thresholds,
               "slide_steps": slide_steps},
    )
    return auc_out, [auc_out], [stat_pos, stat_neg]


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(dtype=input.dtype)
    indices = helper.create_variable_for_type_inference(dtype="int64")
    inputs = {"X": [input]}
    attrs = {}
    if isinstance(k, Variable):
        inputs["K"] = [k]
    else:
        attrs = {"k": k}
    helper.append_op(
        type="top_k",
        inputs=inputs,
        outputs={"Out": [values], "Indices": [indices]},
        attrs=attrs,
    )
    return values, indices


# -- elementwise/reduce/scale family ----------------------------------------


def _elementwise_layer(op_type):
    def layer(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, act=act, name=name)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(
            type=op_type,
            inputs={"X": [x], "Y": [y]},
            outputs={"Out": [out]},
            attrs={"axis": axis},
        )
        return helper.append_activation(out)

    layer.__name__ = op_type
    return layer


elementwise_add = _elementwise_layer("elementwise_add")
elementwise_sub = _elementwise_layer("elementwise_sub")
elementwise_mul = _elementwise_layer("elementwise_mul")
elementwise_div = _elementwise_layer("elementwise_div")
elementwise_max = _elementwise_layer("elementwise_max")
elementwise_min = _elementwise_layer("elementwise_min")
elementwise_pow = _elementwise_layer("elementwise_pow")
elementwise_mod = _elementwise_layer("elementwise_mod")
elementwise_floordiv = _elementwise_layer("elementwise_floordiv")


def _reduce_layer(op_type):
    def layer(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(dtype=input.dtype)
        if dim is None:
            dim_attr = [0]
            reduce_all = True
        else:
            dim_attr = dim if isinstance(dim, (list, tuple)) else [dim]
            reduce_all = False
        helper.append_op(
            type=op_type,
            inputs={"X": [input]},
            outputs={"Out": [out]},
            attrs={"dim": list(dim_attr), "keep_dim": keep_dim,
                   "reduce_all": reduce_all},
        )
        return out

    layer.__name__ = op_type
    return layer


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")
reduce_all = _reduce_layer("reduce_all")
reduce_any = _reduce_layer("reduce_any")


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="scale",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"scale": float(scale), "bias": float(bias),
               "bias_after_scale": bias_after_scale},
    )
    return helper.append_activation(out)


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype=helper.input_dtype("input") if False else input[0].dtype
        )
    helper.append_op(
        type="sum", inputs={"X": input}, outputs={"Out": [out]}
    )
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="clip",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"min": float(min), "max": float(max)},
    )
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="clip_by_norm",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"max_norm": float(max_norm)},
    )
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    """x / sqrt(sum(x^2, axis) + eps) via the norm op (norm_op.cc) — the
    fluid elementwise broadcast rules can't express a same-rank keepdim
    divisor at axis=-1, so this must NOT be composed from elementwise_div."""
    helper = LayerHelper("l2_normalize", name=name)
    norm_out = helper.create_variable_for_type_inference(x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="norm", inputs={"X": [x]},
        outputs={"Norm": [norm_out], "Out": [out]},
        attrs={"axis": int(axis), "epsilon": float(epsilon)},
    )
    return out


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim")
    xn = l2_normalize(X, axis=-1)
    yn = l2_normalize(Y, axis=-1)
    prod = elementwise_mul(xn, yn)
    return reduce_sum(prod, dim=[-1], keep_dim=True)


# -- activations -------------------------------------------------------------

relu = _single_out_layer("relu")
softmax_ = None


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="softmax",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="log_softmax",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def gelu(x, approximate=False):
    helper = LayerHelper("gelu")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="gelu", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"approximate": approximate},
    )
    return out


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="leaky_relu", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"alpha": alpha},
    )
    return out


def elu(x, alpha=1.0, name=None):
    helper = LayerHelper("elu", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="elu", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"alpha": alpha},
    )
    return out


def relu6(x, threshold=6.0, name=None):
    helper = LayerHelper("relu6", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="relu6", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"threshold": threshold},
    )
    return out


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="pow", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"factor": factor},
    )
    return out


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    helper = LayerHelper("hard_sigmoid", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="hard_sigmoid", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"slope": slope, "offset": offset},
    )
    return out


def swish(x, beta=1.0, name=None):
    helper = LayerHelper("swish", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="swish", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"beta": beta},
    )
    return out


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    alpha_shape = [1]
    if mode == "channel":
        alpha_shape = [1, x.shape[1], 1, 1]
    elif mode == "element":
        alpha_shape = list(x.shape[1:])
    from ..initializer import Constant

    alpha = helper.create_parameter(
        attr=param_attr, shape=alpha_shape, dtype=x.dtype,
        default_initializer=Constant(0.25)
    )
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="prelu",
        inputs={"X": [x], "Alpha": [alpha]},
        outputs={"Out": [out]},
        attrs={"mode": mode},
    )
    return out


# -- dropout / label smoothing ----------------------------------------------


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    mask = helper.create_variable_for_type_inference(
        dtype="uint8", stop_gradient=True
    )
    helper.append_op(
        type="dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "fix_seed": seed is not None,
            "seed": seed if seed is not None else 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(
        type="label_smooth",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={"epsilon": float(epsilon)},
    )
    return out


# -- conv / pool / norm (ops registered in ops/nn.py) ------------------------


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    helper = LayerHelper("conv2d", bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    dilation = [dilation, dilation] if isinstance(dilation, int) else list(dilation)
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)

    import math as _math

    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    from ..initializer import Normal

    std = _math.sqrt(2.0 / fan_in)
    w = helper.create_parameter(
        attr=param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=Normal(0.0, std),
    )
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "data_format": data_format,
        },
    )
    if bias_attr is False:
        pre_act = pre_bias
    else:
        b = helper.create_parameter(
            attr=helper.kwargs.get("bias_attr"), shape=[num_filters],
            dtype=dtype, is_bias=True
        )
        if b is None:
            pre_act = pre_bias
        else:
            pre_act = helper.create_variable_for_type_inference(dtype)
            helper.append_op(
                type="elementwise_add",
                inputs={"X": [pre_bias], "Y": [b]},
                outputs={"Out": [pre_act]},
                attrs={"axis": 1 if data_format == "NCHW" else 3},
            )
    return helper.append_activation(pre_act)


def conv2d_bn_relu(input, num_filters, filter_size, stride=1, padding=0,
                   dilation=1, groups=1, param_attr=None, bn_param_attr=None,
                   bn_bias_attr=None, act="relu", momentum=0.9, epsilon=1e-5,
                   is_test=False, moving_mean_name=None,
                   moving_variance_name=None, name=None, data_format="NCHW"):
    """Fused conv + batch-norm (+ relu) trunk block: ONE `conv2d_bn_relu`
    op instead of the conv2d / batch_norm / relu triple, so the lowering
    can route the whole block to the Pallas fused kernel
    (FLAGS_use_pallas_conv_block, probe-gated — pallas_kernels/adoption.py)
    and falls back to the exact composition otherwise.  The conv carries
    no bias: the BN affine absorbs it (the reference's conv_bn_fuse_pass
    precondition).  Only act in (None, "relu") is expressible."""
    if act not in (None, "relu"):
        raise ValueError("conv2d_bn_relu supports act None or 'relu', got %r"
                         % (act,))
    helper = LayerHelper("conv2d_bn_relu", name=name)
    dtype = input.dtype
    num_channels = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    dilation = [dilation, dilation] if isinstance(dilation, int) \
        else list(dilation)
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)

    import math as _math

    from ..initializer import Constant, Normal

    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    w = helper.create_parameter(
        attr=param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=Normal(0.0, _math.sqrt(2.0 / fan_in)),
    )
    scale_p = helper.create_parameter(
        attr=bn_param_attr, shape=[num_filters], dtype=dtype,
        default_initializer=Constant(1.0)
    )
    bias_p = helper.create_parameter(
        attr=bn_bias_attr, shape=[num_filters], dtype=dtype, is_bias=True,
        default_initializer=Constant(0.0)
    )
    mean = helper.create_or_get_global_variable(
        name=moving_mean_name or helper.name + ".mean",
        shape=[num_filters], dtype=dtype, persistable=True
    )
    mean.stop_gradient = True
    variance = helper.create_or_get_global_variable(
        name=moving_variance_name or helper.name + ".var",
        shape=[num_filters], dtype=dtype, persistable=True
    )
    variance.stop_gradient = True
    if not getattr(mean, "_bn_initialized", False):
        Constant(0.0)(mean)
        Constant(1.0)(variance)
        mean._bn_initialized = True
        variance._bn_initialized = True

    saved_mean = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True
    )
    saved_var = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d_bn_relu",
        inputs={
            "Input": [input],
            "Filter": [w],
            "Scale": [scale_p],
            "Bias": [bias_p],
            "Mean": [mean],
            "Variance": [variance],
        },
        outputs={
            "Output": [out],
            "MeanOut": [mean],
            "VarianceOut": [variance],
            "SavedMean": [saved_mean],
            "SavedVariance": [saved_var],
        },
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "data_format": data_format,
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "with_relu": act == "relu",
        },
    )
    return out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    helper = LayerHelper("conv2d_transpose", bias_attr=bias_attr, act=act,
                         name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    groups = groups or 1
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    dilation = [dilation, dilation] if isinstance(dilation, int) else list(dilation)
    if filter_size is None:
        raise ValueError("filter_size required")
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(attr=param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "output_size": list(output_size) if output_size else [],
            "data_format": data_format,
        },
    )
    if bias_attr is False:
        pre_act = pre_bias
    else:
        b = helper.create_parameter(
            attr=helper.kwargs.get("bias_attr"), shape=[num_filters],
            dtype=dtype, is_bias=True
        )
        if b is None:
            pre_act = pre_bias
        else:
            pre_act = helper.create_variable_for_type_inference(dtype)
            helper.append_op(
                type="elementwise_add",
                inputs={"X": [pre_bias], "Y": [b]},
                outputs={"Out": [pre_act]},
                attrs={"axis": 1},
            )
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCHW"):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    pool_size = [pool_size, pool_size] if isinstance(pool_size, int) else list(pool_size)
    pool_stride = [pool_stride, pool_stride] if isinstance(pool_stride, int) else list(pool_stride)
    pool_padding = [pool_padding, pool_padding] if isinstance(pool_padding, int) else list(pool_padding)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": pool_size,
            "strides": pool_stride,
            "paddings": pool_padding,
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
            "data_format": data_format,
        },
    )
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    helper = LayerHelper("adaptive_pool2d", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    pool_size = [pool_size, pool_size] if isinstance(pool_size, int) else list(pool_size)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": pool_size,
            "adaptive": True,
            "strides": [1, 1],
            "paddings": [0, 0],
        },
    )
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False,
               use_global_stats=False):
    helper = LayerHelper("batch_norm", act=act, name=name)
    dtype = input.dtype
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    from ..initializer import Constant

    scale_p = helper.create_parameter(
        attr=param_attr, shape=[c], dtype=dtype,
        default_initializer=Constant(1.0)
    )
    bias_p = helper.create_parameter(
        attr=bias_attr, shape=[c], dtype=dtype, is_bias=True,
        default_initializer=Constant(0.0)
    )
    mean = helper.create_or_get_global_variable(
        name=moving_mean_name or helper.name + ".mean",
        shape=[c], dtype=dtype, persistable=True
    )
    mean.stop_gradient = True
    variance = helper.create_or_get_global_variable(
        name=moving_variance_name or helper.name + ".var",
        shape=[c], dtype=dtype, persistable=True
    )
    variance.stop_gradient = True
    if not getattr(mean, "_bn_initialized", False):
        Constant(0.0)(mean)
        Constant(1.0)(variance)
        mean._bn_initialized = True
        variance._bn_initialized = True

    saved_mean = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True
    )
    saved_var = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="batch_norm",
        inputs={
            "X": [input],
            "Scale": [scale_p],
            "Bias": [bias_p],
            "Mean": [mean],
            "Variance": [variance],
        },
        outputs={
            "Y": [out],
            "MeanOut": [mean],
            "VarianceOut": [variance],
            "SavedMean": [saved_mean],
            "SavedVariance": [saved_var],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
            # ghost-batch statistics (FLAGS_bn_stat_subsample, default 1 =
            # exact): estimate batch stats from every k-th sample — cuts the
            # dominant stat-pass HBM traffic on bandwidth-bound devices
            "stat_subsample": int(_flags.flag("bn_stat_subsample") or 1),
        },
    )
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", act=act, name=name)
    dtype = input.dtype
    norm_size = 1
    for d in input.shape[begin_norm_axis:]:
        norm_size *= int(d)
    inputs = {"X": [input]}
    from ..initializer import Constant

    scale_p = bias_p = None
    if scale:
        scale_p = helper.create_parameter(
            attr=param_attr, shape=[norm_size], dtype=dtype,
            default_initializer=Constant(1.0)
        )
        inputs["Scale"] = [scale_p]
    if shift:
        bias_p = helper.create_parameter(
            attr=bias_attr, shape=[norm_size], dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [bias_p]
    out = helper.create_variable_for_type_inference(dtype)
    mean_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="layer_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean_out], "Variance": [var_out]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return helper.append_activation(out)


def fused_dropout_add_ln(x, y, dropout_prob=0.0, is_test=False,
                         begin_norm_axis=1, epsilon=1e-5, param_attr=None,
                         bias_attr=None, name=None, seed=None):
    """LayerNorm(x + dropout(y)) as ONE op — the transformer-encoder
    epilogue, lowered to a fused single-pass Pallas kernel on TPU (see
    ops/nn.py fused_dropout_add_ln; reference analog:
    paddle/fluid/operators/fused/fused_fc_elementwise_layernorm_op.cu,
    extended with in-kernel dropout for training).  Exactly equivalent to

        layer_norm(elementwise_add(x, dropout(y, dropout_prob,
                   dropout_implementation="upscale_in_train")), ...)

    with dropout's keep probability realized at 2^-32 granularity."""
    helper = LayerHelper("fused_dropout_add_ln", name=name)
    dtype = x.dtype
    norm_size = 1
    for d in x.shape[begin_norm_axis:]:
        norm_size *= int(d)
    from ..initializer import Constant

    scale_p = helper.create_parameter(
        attr=param_attr, shape=[norm_size], dtype=dtype,
        default_initializer=Constant(1.0))
    bias_p = helper.create_parameter(
        attr=bias_attr, shape=[norm_size], dtype=dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(dtype)
    r_out = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    mean_out = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    seed_out = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True)
    helper.append_op(
        type="fused_dropout_add_ln",
        inputs={"X": [x], "Y": [y], "Scale": [scale_p], "Bias": [bias_p]},
        outputs={"Out": [out], "R": [r_out], "Mean": [mean_out],
                 "Variance": [var_out], "Seed": [seed_out]},
        attrs={"dropout_prob": float(dropout_prob), "is_test": is_test,
               "epsilon": epsilon, "begin_norm_axis": begin_norm_axis,
               "fix_seed": seed is not None, "seed": seed or 0},
    )
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", act=act, name=name)
    dtype = input.dtype
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    from ..initializer import Constant

    inputs = {"X": [input]}
    if param_attr is not False:
        scale_p = helper.create_parameter(
            attr=param_attr, shape=[c], dtype=dtype,
            default_initializer=Constant(1.0)
        )
        inputs["Scale"] = [scale_p]
    if bias_attr is not False:
        bias_p = helper.create_parameter(
            attr=bias_attr, shape=[c], dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [bias_p]
    out = helper.create_variable_for_type_inference(dtype)
    mean_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="group_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean_out], "Variance": [var_out]},
        attrs={"epsilon": epsilon, "groups": groups,
               "data_layout": data_layout},
    )
    return helper.append_activation(out)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm", name=name)
    dtype = input.dtype
    c = input.shape[1]
    from ..initializer import Constant

    scale_p = helper.create_parameter(
        attr=param_attr, shape=[c], dtype=dtype,
        default_initializer=Constant(1.0)
    )
    bias_p = helper.create_parameter(
        attr=bias_attr, shape=[c], dtype=dtype, is_bias=True
    )
    out = helper.create_variable_for_type_inference(dtype)
    mean_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="instance_norm",
        inputs={"X": [input], "Scale": [scale_p], "Bias": [bias_p]},
        outputs={"Y": [out], "SavedMean": [mean_out],
                 "SavedVariance": [var_out]},
        attrs={"epsilon": epsilon},
    )
    return out


# -- shape manipulation ------------------------------------------------------


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    xshape = helper.create_variable_for_type_inference(
        dtype=x.dtype, stop_gradient=True
    )
    helper.append_op(
        type="transpose2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axis": list(perm)},
    )
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", act=act, name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    xshape = helper.create_variable_for_type_inference(
        dtype=x.dtype, stop_gradient=True
    )
    helper.append_op(
        type="reshape2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"shape": list(shape)},
    )
    return helper.append_activation(out)


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    xshape = helper.create_variable_for_type_inference(
        dtype=input.dtype, stop_gradient=True
    )
    helper.append_op(
        type="squeeze2",
        inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axes": list(axes)},
    )
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    xshape = helper.create_variable_for_type_inference(
        dtype=input.dtype, stop_gradient=True
    )
    helper.append_op(
        type="unsqueeze2",
        inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axes": list(axes)},
    )
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    xshape = helper.create_variable_for_type_inference(
        dtype=x.dtype, stop_gradient=True
    )
    helper.append_op(
        type="flatten2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axis": axis},
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op(
        type="concat",
        inputs={"X": input},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        attrs = {"num": num, "axis": dim, "sections": []}
        n_out = num
    else:
        attrs = {"num": 0, "axis": dim, "sections": list(num_or_sections)}
        n_out = len(num_or_sections)
    outs = [
        helper.create_variable_for_type_inference(dtype=input.dtype)
        for _ in range(n_out)
    ]
    helper.append_op(
        type="split", inputs={"X": [input]}, outputs={"Out": outs}, attrs=attrs
    )
    return outs


def stack(x, axis=0):
    helper = LayerHelper("stack")
    x = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(dtype=x[0].dtype)
    helper.append_op(
        type="stack", inputs={"X": x}, outputs={"Y": [out]},
        attrs={"axis": axis},
    )
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    if num is None:
        num = x.shape[axis]
    outs = [
        helper.create_variable_for_type_inference(dtype=x.dtype)
        for _ in range(num)
    ]
    helper.append_op(
        type="unstack", inputs={"X": [x]}, outputs={"Y": outs},
        attrs={"axis": axis, "num": num},
    )
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="expand", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"expand_times": list(expand_times)},
    )
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="slice",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"axes": list(axes), "starts": list(starts),
               "ends": list(ends)},
    )
    return out


def strided_slice(input, axes, starts, ends, strides):
    helper = LayerHelper("strided_slice")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="strided_slice",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"axes": list(axes), "starts": list(starts),
               "ends": list(ends), "strides": list(strides)},
    )
    return out


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="gather",
        inputs={"X": [input], "Index": [index]},
        outputs={"Out": [out]},
        attrs={"overwrite": overwrite},
    )
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="gather_nd",
        inputs={"X": [input], "Index": [index]},
        outputs={"Out": [out]},
    )
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]},
        attrs={"overwrite": overwrite},
    )
    return out


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(
        type="one_hot",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"depth": depth, "allow_out_of_range": allow_out_of_range},
    )
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="pad",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "pad_value": float(pad_value)},
    )
    return out


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="pad2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "mode": mode,
               "pad_value": float(pad_value), "data_format": data_format},
    )
    return out


def lod_reset(x, y=None, target_lod=None):
    # LoD is metadata-only on TPU (masks/padding carry sequence info)
    return x


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="shape", inputs={"Input": [input]}, outputs={"Out": [out]}
    )
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="arg_max", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="arg_min", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    ids = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="argsort",
        inputs={"X": [input]},
        outputs={"Out": [out], "Indices": [ids]},
        attrs={"axis": axis, "descending": descending},
    )
    return out, ids


def where(condition):
    helper = LayerHelper("where_index")
    out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="where_index",
        inputs={"Condition": [condition]},
        outputs={"Out": [out]},
    )
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="uniform_random_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx, "min": min, "max": max,
               "seed": seed, "dtype": dtype_enum(dtype)},
    )
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gaussian_random",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "mean": mean, "std": std, "seed": seed,
               "dtype": dtype_enum(dtype)},
    )
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1, data_format="NCHW"):
    helper = LayerHelper("image_resize", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    if out_shape is None:
        h = int(input.shape[2] * scale)
        w = int(input.shape[3] * scale)
        out_shape = [h, w]
    helper.append_op(
        type="bilinear_interp" if resample.upper() == "BILINEAR" else "nearest_interp",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"out_h": int(out_shape[0]), "out_w": int(out_shape[1]),
               "align_corners": align_corners, "align_mode": align_mode,
               "data_layout": data_format},
    )
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        actual_shape, align_corners, align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        actual_shape, align_corners)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    helper = LayerHelper("unfold", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    ks = [kernel_sizes] * 2 if isinstance(kernel_sizes, int) else list(kernel_sizes)
    st = [strides] * 2 if isinstance(strides, int) else list(strides)
    pd = [paddings] * 4 if isinstance(paddings, int) else list(paddings)
    dl = [dilations] * 2 if isinstance(dilations, int) else list(dilations)
    helper.append_op(
        type="unfold",
        inputs={"X": [x]},
        outputs={"Y": [out]},
        attrs={"kernel_sizes": ks, "strides": st, "paddings": pd,
               "dilations": dl},
    )
    return out


def pixel_shuffle(x, upscale_factor):
    helper = LayerHelper("pixel_shuffle")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="pixel_shuffle",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"upscale_factor": upscale_factor},
    )
    return out


def flash_attention(q, k, v, bias_qk=None, causal=False, scale=0.0,
                    layout="BHSD", dropout_prob=0.0, is_test=False,
                    name=None):
    """Fused blockwise multi-head attention (Pallas TPU kernel; see
    paddle_tpu/pallas_kernels/flash_attention.py).  Analog of the
    reference's fused attention (multihead_matmul_op.cu) but
    differentiable/trainable.

    layout: "BHSD" (default) or "BSHD" ([B, S, H, D] — transpose-free
    emission: split heads with a reshape, no transpose, no relayout
    copies).  dropout_prob > 0 applies attention-prob dropout inside the
    op when not is_test.  bias_qk is an additive mask (no gradient flows
    to it).  scale=0.0 means "use 1/sqrt(head_dim)"; pass scale=1.0 if q
    is already pre-scaled."""
    helper = LayerHelper("flash_attention", name=name)
    out = helper.create_variable_for_type_inference(dtype=q.dtype)
    # Mask must be DECLARED: with dropout active the custom grad replays
    # with this saved mask (an undeclared slot would silently drop it and
    # the backward would run mask-free — decoupled from the sampled loss).
    # On the small-seq fused-kernel path the mask is never materialized:
    # Seed (2 words) + Lse replay it instead (see ops/nn.py).
    mask = helper.create_variable_for_type_inference(dtype="uint8")
    mask.stop_gradient = True
    seed_out = helper.create_variable_for_type_inference(dtype="int32")
    seed_out.stop_gradient = True
    lse = helper.create_variable_for_type_inference(dtype="float32")
    lse.stop_gradient = True
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if bias_qk is not None:
        inputs["BiasQK"] = [bias_qk]
    helper.append_op(
        type="flash_attention",
        inputs=inputs,
        outputs={"Out": [out], "Mask": [mask], "Seed": [seed_out],
                 "Lse": [lse]},
        attrs={"causal": causal, "scale": float(scale),
               "layout": layout, "dropout_prob": float(dropout_prob),
               "is_test": is_test},
    )
    return out


def ring_attention(q, k, v, causal=False, scale=0.0, axis="sp", name=None):
    """Context-parallel ring attention over mesh axis `axis` (sequence dim
    sharded); dense flash attention when unsharded.  See
    paddle_tpu/parallel/ring_attention.py."""
    helper = LayerHelper("ring_attention", name=name)
    out = helper.create_variable_for_type_inference(dtype=q.dtype)
    helper.append_op(
        type="ring_attention",
        inputs={"Q": [q], "K": [k], "V": [v]},
        outputs={"Out": [out]},
        attrs={"causal": causal, "scale": float(scale), "axis": axis},
    )
    return out
