"""Tensor creation / IO layer API (parity: layers/tensor.py + layers/io.py
`data`)."""

import numpy as np

from ..framework import (
    Variable,
    convert_np_dtype_to_dtype_,
    default_main_program,
    default_startup_program,
)
from ..layer_helper import LayerHelper
from ..ops.common import dtype_enum

__all__ = [
    "data",
    "create_tensor",
    "create_parameter",
    "create_global_var",
    "cast",
    "concat",
    "sums",
    "assign",
    "fill_constant",
    "fill_constant_batch_size_like",
    "ones",
    "zeros",
    "ones_like",
    "zeros_like",
    "reverse",
    "has_inf",
    "has_nan",
    "isfinite",
    "range",
    "linspace",
    "diag",
    "eye",
    "argmax",
    "argmin",
]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         type=None, stop_gradient=True):
    """Declare an input variable (reference layers/io.py:data / fluid.data).

    With append_batch_size=True a leading -1 batch dim is added.
    """
    helper = LayerHelper("data")
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper.block.program.global_block().create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        is_data=True,
        need_check_feed=True,
        stop_gradient=stop_gradient,
    )


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(
        name=helper.name, dtype=dtype, persistable=persistable
    )


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..param_attr import ParamAttr

    helper = LayerHelper("create_parameter", name=name)
    attr = ParamAttr._to_attr(attr)
    if name is not None and attr.name is None:
        attr.name = name
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        dtype=dtype, shape=list(shape), persistable=persistable,
        name=name
    )
    from ..initializer import Constant

    helper.set_variable_initializer(var, Constant(value))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast")
    dtype = convert_np_dtype_to_dtype_(dtype)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="cast",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"in_dtype": dtype_enum(x.dtype), "out_dtype": dtype_enum(dtype)},
    )
    return out


def concat(input, axis=0, name=None):
    from .nn import concat as _concat

    return _concat(input, axis, name)


def sums(input, out=None):
    from .nn import sums as _sums

    return _sums(input, out)


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(dtype=input.dtype)
        helper.append_op(
            type="assign", inputs={"X": [input]}, outputs={"Out": [output]}
        )
        return output
    arr = np.asarray(input)
    if output is None:
        output = helper.create_variable_for_type_inference(
            dtype=convert_np_dtype_to_dtype_(arr.dtype)
        )
    key = {
        "float32": "fp32_values",
        "int32": "int32_values",
        "int64": "int64_values",
        "bool": "bool_values",
    }.get(convert_np_dtype_to_dtype_(arr.dtype), "fp32_values")
    helper.append_op(
        type="assign_value",
        outputs={"Out": [output]},
        attrs={
            "shape": list(arr.shape),
            "dtype": dtype_enum(convert_np_dtype_to_dtype_(arr.dtype)),
            key: [float(v) if key == "fp32_values" else int(v)
                  for v in arr.flatten()],
        },
    )
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    dtype = convert_np_dtype_to_dtype_(dtype)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": dtype_enum(dtype),
               "value": float(value), "force_cpu": force_cpu},
    )
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  force_cpu=False):
    helper = LayerHelper("fill_constant_batch_size_like")
    dtype = convert_np_dtype_to_dtype_(dtype)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": dtype_enum(dtype),
               "value": float(value), "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx},
    )
    out.stop_gradient = True
    return out


def ones(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 1.0, force_cpu)


def zeros(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 0.0, force_cpu)


def ones_like(x, out=None):
    helper = LayerHelper("ones_like")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="fill_any_like",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"value": 1.0},
    )
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="fill_zeros_like", inputs={"X": [x]}, outputs={"Out": [out]}
    )
    return out


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="reverse",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": [axis] if isinstance(axis, int) else list(axis)},
    )
    return out


def has_inf(x):
    helper = LayerHelper("isinf")
    out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="isinf_v2", inputs={"X": [x]}, outputs={"Out": [out]})
    from .nn import reduce_any

    return reduce_any(out)


def has_nan(x):
    helper = LayerHelper("isnan")
    out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="isnan_v2", inputs={"X": [x]}, outputs={"Out": [out]})
    from .nn import reduce_any

    return reduce_any(out)


def isfinite(x):
    helper = LayerHelper("isfinite")
    out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="isfinite", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def range(start, end, step, dtype):
    helper = LayerHelper("range")
    dtype = convert_np_dtype_to_dtype_(dtype)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    # concrete bounds only (static shapes on TPU)
    import numpy as _np

    arr = _np.arange(start, end, step)
    return assign(arr.astype(dtype), out)


def linspace(start, stop, num, dtype="float32"):
    helper = LayerHelper("linspace")
    from . import tensor as _t

    start_v = fill_constant([1], dtype, start) if not isinstance(start, Variable) else start
    stop_v = fill_constant([1], dtype, stop) if not isinstance(stop, Variable) else stop
    num_v = fill_constant([1], "int32", num) if not isinstance(num, Variable) else num
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="linspace",
        inputs={"Start": [start_v], "Stop": [stop_v], "Num": [num_v]},
        outputs={"Out": [out]},
        attrs={"dtype": dtype_enum(convert_np_dtype_to_dtype_(dtype))},
    )
    return out


def diag(diagonal):
    import numpy as _np

    helper = LayerHelper("diag")
    if isinstance(diagonal, Variable):
        from .nn import _single_out_layer

        raise NotImplementedError("diag of Variable: use layers.eye composition")
    arr = _np.diag(_np.asarray(diagonal))
    return assign(arr)


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32"):
    helper = LayerHelper("eye")
    dtype = convert_np_dtype_to_dtype_(dtype)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="eye",
        outputs={"Out": [out]},
        attrs={"num_rows": num_rows,
               "num_columns": num_columns if num_columns else num_rows,
               "dtype": dtype_enum(dtype)},
    )
    if batch_shape:
        from .nn import expand, unsqueeze

        for _ in batch_shape:
            out = unsqueeze(out, [0])
        out = expand(out, list(batch_shape) + [1, 1])
    return out


def argmax(x, axis=0):
    from .nn import argmax as _argmax

    return _argmax(x, axis)


def argmin(x, axis=0):
    from .nn import argmin as _argmin

    return _argmin(x, axis)
