"""Mixture-of-experts layer (NEW capability vs the reference — EP is
absent in the 2019 codebase).  Kernel: parallel/moe.py; op: ops/collective.py
moe_ffn."""

from ..layer_helper import LayerHelper

__all__ = ["moe"]


def moe(input, num_experts, hidden_size, top_k=2, capacity_factor=1.25,
        param_attr=None, expert_parallel_axis=None, name=None):
    """Mixture-of-experts FFN layer (NEW capability vs the reference — EP
    is absent in the 2019 codebase; see parallel/moe.py).  input [..., D];
    returns (out [..., D], aux_loss scalar).  `expert_parallel_axis` maps
    to a mesh-axis ring_id for shard_map EP; None shards via auto-SPMD
    (expert dim annotated over the "expert" axis when present)."""
    from ..param_attr import ParamAttr
    from ..initializer import Normal

    helper = LayerHelper("moe", name=name)
    dtype = input.dtype
    D, H, E = input.shape[-1], hidden_size, num_experts

    def attr(suffix, shard):
        base = param_attr if isinstance(param_attr, ParamAttr) else None
        a = ParamAttr(
            name=((base.name if base and base.name else helper.name)
                  + "_" + suffix),
            initializer=(base.initializer if base else None),
            sharding=shard if expert_parallel_axis is None else None)
        return a

    gate_w = helper.create_parameter(
        attr=attr("gate", None), shape=[D, E], dtype=dtype,
        default_initializer=Normal(0.0, 0.02))
    w1 = helper.create_parameter(
        attr=attr("w1", ("expert", None, None)), shape=[E, D, H],
        dtype=dtype, default_initializer=Normal(0.0, 0.02))
    b1 = helper.create_parameter(
        attr=attr("b1", ("expert", None)), shape=[E, H], dtype=dtype,
        is_bias=True)
    w2 = helper.create_parameter(
        attr=attr("w2", ("expert", None, None)), shape=[E, H, D],
        dtype=dtype, default_initializer=Normal(0.0, 0.02))
    b2 = helper.create_parameter(
        attr=attr("b2", ("expert", None)), shape=[E, D], dtype=dtype,
        is_bias=True)
    out = helper.create_variable_for_type_inference(dtype)
    aux = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="moe_ffn",
        inputs={"X": [input], "GateW": [gate_w], "W1": [w1], "B1": [b1],
                "W2": [w2], "B2": [b2]},
        outputs={"Out": [out], "AuxLoss": [aux]},
        attrs={"top_k": top_k, "capacity_factor": capacity_factor,
               "axis_name": (expert_parallel_axis
                             if isinstance(expert_parallel_axis, str)
                             else ""),
               "ring_id": (expert_parallel_axis
                           if isinstance(expert_parallel_axis, int)
                           else -1)})
    return out, aux
