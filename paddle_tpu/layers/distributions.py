"""Probability distributions (parity: python/paddle/fluid/layers/
distributions.py:28-633): Uniform, Normal, Categorical,
MultivariateNormalDiag, composed from layer ops so they work in static
graphs and dygraph alike."""

import math

import numpy as np

from ..framework import Variable
from . import tensor
from . import control_flow

__all__ = ["Distribution", "Uniform", "Normal", "Categorical",
           "MultivariateNormalDiag"]


def _lay():
    import paddle_tpu.layers as _L

    return _L


def _log(x):
    return _lay().log(x)


def _exp(x):
    return _lay().exp(x)


class Distribution(object):
    """Abstract base (distributions.py:28)."""

    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def _to_variable(self, *args):
        out = []
        for a in args:
            if isinstance(a, Variable):
                out.append(a)
            else:
                arr = np.array(a, dtype="float32")
                if arr.ndim == 0:
                    arr = arr.reshape(1)
                v = tensor.create_tensor(dtype="float32")
                tensor.assign(arr, v)
                out.append(v)
        return tuple(out)

    def _validate_args(self, *args):
        is_var = [isinstance(a, Variable) for a in args]
        if any(is_var) and not all(is_var):
            return False
        return all(is_var)


class Uniform(Distribution):
    """U(low, high) (distributions.py:113)."""

    def __init__(self, low, high):
        self.all_arg_is_float = (isinstance(low, float)
                                 and isinstance(high, float))
        self.low, self.high = self._to_variable(low, high)

    def sample(self, shape, seed=0):
        batch_shape = list((self.low + self.high).shape)
        output_shape = list(shape) + batch_shape
        u = _lay().uniform_random(output_shape, seed=seed, min=0.0, max=1.0)
        out = u * (tensor.zeros(output_shape, dtype="float32")
                   + (self.high - self.low)) + self.low
        if self.all_arg_is_float:
            return _lay().reshape(out, shape)
        return out

    def log_prob(self, value):
        lb = tensor.cast(control_flow.less_than(self.low, value),
                         dtype=value.dtype)
        ub = tensor.cast(control_flow.less_than(value, self.high),
                         dtype=value.dtype)
        return _log(lb * ub) - _log(self.high - self.low)

    def entropy(self):
        return _log(self.high - self.low)


class Normal(Distribution):
    """N(loc, scale) (distributions.py:247)."""

    def __init__(self, loc, scale):
        self.all_arg_is_float = (isinstance(loc, float)
                                 and isinstance(scale, float))
        self.loc, self.scale = self._to_variable(loc, scale)

    def sample(self, shape, seed=0):
        batch_shape = list((self.loc + self.scale).shape)
        output_shape = list(shape) + batch_shape
        z = _lay().gaussian_random(output_shape, mean=0.0, std=1.0, seed=seed)
        out = z * (tensor.zeros(output_shape, dtype="float32")
                   + self.scale) + self.loc
        if self.all_arg_is_float:
            return _lay().reshape(out, shape)
        return out

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + _log(self.scale)

    def log_prob(self, value):
        var = self.scale * self.scale
        log_scale = _log(self.scale)
        return (-1.0 * ((value - self.loc) * (value - self.loc)) / (2.0 * var)
                - log_scale - math.log(math.sqrt(2.0 * math.pi)))

    def kl_divergence(self, other):
        assert isinstance(other, Normal), \
            "another distribution must be Normal"
        var_ratio = self.scale / other.scale
        var_ratio = var_ratio * var_ratio
        t1 = (self.loc - other.loc) / other.scale
        t1 = t1 * t1
        return 0.5 * (var_ratio + t1 - 1.0 - _log(var_ratio))


class Categorical(Distribution):
    """Categorical over unnormalized logits (distributions.py:400); the
    reference implements entropy and kl_divergence only."""

    def __init__(self, logits):
        if isinstance(logits, Variable):
            self.logits = logits
        else:
            (self.logits,) = self._to_variable(logits)

    def _norm(self, logits):
        shifted = logits - _lay().reduce_max(logits, dim=-1, keep_dim=True)
        e = _exp(shifted)
        z = _lay().reduce_sum(e, dim=-1, keep_dim=True)
        return shifted, e, z

    def kl_divergence(self, other):
        assert isinstance(other, Categorical)
        logits, e, z = self._norm(self.logits)
        o_logits, o_e, o_z = self._norm(other.logits)
        prob = e / z
        return _lay().reduce_sum(
            prob * (logits - _log(z) - o_logits + _log(o_z)),
            dim=-1, keep_dim=True)

    def entropy(self):
        logits, e, z = self._norm(self.logits)
        prob = e / z
        return -1.0 * _lay().reduce_sum(prob * (logits - _log(z)),
                                    dim=-1, keep_dim=True)


class MultivariateNormalDiag(Distribution):
    """Multivariate normal with diagonal covariance given as a full [k,k]
    matrix (distributions.py:503)."""

    def __init__(self, loc, scale):
        self.loc, self.scale = self._to_variable(loc, scale)

    def _det(self, value):
        k = value.shape[0]
        one_diag = tensor.eye(k, dtype=value.dtype)
        return _lay().reduce_prod(_lay().reduce_sum(value * one_diag, dim=-1))

    def _inv(self, value):
        k = value.shape[0]
        one_diag = tensor.eye(k, dtype=value.dtype)
        one_all = tensor.fill_constant([k, k], value.dtype, 1.0)
        # exponent is -1 on the diagonal (1/x) and 1 off it (0 stays 0)
        return _lay().elementwise_pow(value, one_all - 2.0 * one_diag)

    def entropy(self):
        k = self.scale.shape[0]
        return 0.5 * (k * (1.0 + math.log(2 * math.pi))
                      + _log(self._det(self.scale)))

    def kl_divergence(self, other):
        assert isinstance(other, MultivariateNormalDiag)
        tr = _lay().reduce_sum(self._inv(other.scale) * self.scale)
        d = other.loc - self.loc
        loc_cov = _lay().matmul(d, self._inv(other.scale))
        tri = _lay().matmul(loc_cov, _lay().transpose(d, [1, 0])
                        if len(d.shape) == 2 else d)
        k = list(self.scale.shape)[0]
        ln_cov = _log(self._det(other.scale)) - _log(
            self._det(self.scale))
        return 0.5 * (tr + tri - k + ln_cov)
