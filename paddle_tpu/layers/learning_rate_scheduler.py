"""LR schedules as in-graph ops (parity:
python/paddle/fluid/layers/learning_rate_scheduler.py): noam, exponential,
natural_exp, inverse_time, polynomial, piecewise, cosine, warmup."""

import math

from ..framework import default_main_program
from ..initializer import Constant
from ..layer_helper import LayerHelper

__all__ = [
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "noam_decay",
    "cosine_decay",
    "linear_lr_warmup",
]


def _decay_step_counter(begin=0):
    helper = LayerHelper("global_step_counter")
    counter = helper.create_or_get_global_variable(
        name="@LR_DECAY_COUNTER@", dtype="float32", shape=[1],
        persistable=True
    )
    counter.stop_gradient = True
    program = default_main_program()
    already = any(
        op.type == "increment" and op.output("Out") == [counter.name]
        for op in program.global_block().ops
    )
    if not already:
        # init to begin - 1: the increment runs BEFORE the schedule math
        # each step, so the first observed value is `begin` — matching
        # the reference's autoincreased_step_counter (step 0 on the first
        # run, noam's begin=1 counter starting at 1)
        Constant(float(begin) - 1.0)(counter)
        with program._lr_schedule_guard():
            program.global_block().append_op(
                type="increment",
                inputs={"X": [counter]},
                outputs={"Out": [counter]},
                attrs={"step": 1.0},
            )
    return counter




def _unary(op_type, x):
    """Append a single-input activation op (exp/floor/ceil/cos live in the
    op registry, not the nn module namespace)."""
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]})
    return out

def noam_decay(d_model, warmup_steps):
    from . import nn, tensor

    program = default_main_program()
    with program._lr_schedule_guard():
        step = _decay_step_counter(begin=1)
        a = nn.pow(step, factor=-0.5)
        b = nn.scale(step, scale=warmup_steps ** -1.5)
        lr = nn.scale(
            nn.elementwise_min(a, b), scale=d_model ** -0.5
        )
    return lr


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from . import nn

    program = default_main_program()
    with program._lr_schedule_guard():
        step = _decay_step_counter()
        div = nn.scale(step, scale=1.0 / decay_steps)
        if staircase:
            div = _unary("floor", div)
        lr = nn.scale(
            nn.elementwise_pow(_const_like(div, decay_rate), div),
            scale=float(learning_rate),
        )
    return lr


def _const_like(ref, value):
    from . import tensor

    return tensor.fill_constant([1], ref.dtype, value)


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from . import nn

    program = default_main_program()
    with program._lr_schedule_guard():
        step = _decay_step_counter()
        div = nn.scale(step, scale=1.0 / decay_steps)
        if staircase:
            div = _unary("floor", div)
        # lr * exp(-decay_rate * t)
        ex = _unary("exp", nn.scale(div, scale=-decay_rate))
        lr = nn.scale(ex, scale=float(learning_rate))
    return lr


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    from . import nn, tensor

    program = default_main_program()
    with program._lr_schedule_guard():
        step = _decay_step_counter()
        div = nn.scale(step, scale=1.0 / decay_steps)
        if staircase:
            div = _unary("floor", div)
        denom = nn.scale(div, scale=decay_rate, bias=1.0)
        lr = nn.elementwise_div(
            tensor.fill_constant([1], "float32", float(learning_rate)), denom
        )
    return lr


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    from . import nn, tensor

    program = default_main_program()
    with program._lr_schedule_guard():
        step = _decay_step_counter()
        if cycle:
            ratio = nn.scale(step, scale=1.0 / decay_steps)
            div = _unary("ceil", nn.elementwise_max(
                ratio, tensor.fill_constant([1], "float32", 1e-12)))
            steps = nn.scale(div, scale=float(decay_steps))
        else:
            steps = tensor.fill_constant([1], "float32", float(decay_steps))
            step = nn.elementwise_min(step, steps)
        frac = nn.elementwise_div(step, steps)
        one_minus = nn.scale(frac, scale=-1.0, bias=1.0)
        powed = nn.pow(one_minus, factor=power)
        lr = nn.scale(powed, scale=float(learning_rate - end_learning_rate),
                      bias=float(end_learning_rate))
    return lr


def piecewise_decay(boundaries, values):
    """sum_i values[i] * 1[b_{i-1} <= step < b_i]"""
    from . import nn, tensor

    assert len(boundaries) + 1 == len(values)
    program = default_main_program()
    with program._lr_schedule_guard():
        step = _decay_step_counter()
        pieces = []
        prev = None
        for i, v in enumerate(values):
            if i == 0:
                cond = step < tensor.fill_constant([1], "float32",
                                                  float(boundaries[0]))
            elif i < len(boundaries):
                lo = tensor.fill_constant([1], "float32",
                                          float(boundaries[i - 1]))
                hi = tensor.fill_constant([1], "float32",
                                          float(boundaries[i]))
                from .. import layers as L

                cond = L.logical_and(step >= lo, step < hi)
            else:
                lo = tensor.fill_constant([1], "float32",
                                          float(boundaries[-1]))
                cond = step >= lo
            ind = tensor.cast(cond, "float32")
            pieces.append(nn.scale(ind, scale=float(v)))
        lr = pieces[0]
        for p in pieces[1:]:
            lr = nn.elementwise_add(lr, p)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    from . import nn

    program = default_main_program()
    with program._lr_schedule_guard():
        step = _decay_step_counter()
        epoch = _unary("floor", nn.scale(step, scale=1.0 / step_each_epoch))
        cos_arg = nn.scale(epoch, scale=math.pi / epochs)
        # lr = 0.5 * base * (cos(epoch*pi/epochs) + 1)
        lr = nn.scale(_cos(cos_arg), scale=0.5 * learning_rate,
                      bias=0.5 * learning_rate)
    return lr


def _cos(x):
    from ..layer_helper import LayerHelper

    helper = LayerHelper("cos")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="cos", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """lr = start + (end-start)*step/warmup while step<warmup else base."""
    from . import nn, tensor

    program = default_main_program()
    with program._lr_schedule_guard():
        step = _decay_step_counter()
        wsteps = tensor.fill_constant([1], "float32", float(warmup_steps))
        frac = nn.elementwise_div(nn.elementwise_min(step, wsteps), wsteps)
        warm = nn.scale(frac, scale=float(end_lr - start_lr),
                        bias=float(start_lr))
        in_warm = tensor.cast(step < wsteps, "float32")
        if not hasattr(learning_rate, "name"):
            learning_rate = tensor.fill_constant(
                [1], "float32", float(learning_rate))
        after = nn.elementwise_mul(
            learning_rate, nn.scale(in_warm, scale=-1.0, bias=1.0))
        lr = nn.elementwise_add(nn.elementwise_mul(warm, in_warm), after)
    return lr
