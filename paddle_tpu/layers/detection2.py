"""Layer wrappers for the detection long tail (parity:
python/paddle/fluid/layers/detection.py + the deformable/psroi entries of
layers/nn.py).  Ops live in ops/detection2.py; ragged-output reference
semantics become fixed-size padded outputs (see the op docstrings)."""

from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "polygon_box_transform", "yolov3_loss", "psroi_pool", "prroi_pool",
    "roi_perspective_transform", "deformable_conv", "deformable_roi_pooling",
    "generate_proposals", "rpn_target_assign", "retinanet_target_assign",
    "generate_proposal_labels", "generate_mask_labels",
    "retinanet_detection_output", "locality_aware_nms",
    "distribute_fpn_proposals", "collect_fpn_proposals",
    "box_decoder_and_assign", "similarity_focus", "filter_by_instag",
    "continuous_value_model",
]


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="polygon_box_transform", inputs={"Input": [input]},
                     outputs={"Output": [out]})
    return out


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    helper = LayerHelper("yolov3_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    obj_mask = helper.create_variable_for_type_inference(x.dtype)
    gt_match = helper.create_variable_for_type_inference("int32")
    inputs = {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]}
    if gt_score is not None:
        inputs["GTScore"] = [gt_score]
    helper.append_op(
        type="yolov3_loss", inputs=inputs,
        outputs={"Loss": [loss], "ObjectnessMask": [obj_mask],
                 "GTMatchMask": [gt_match]},
        attrs={"anchors": [int(a) for a in anchors],
               "anchor_mask": [int(a) for a in anchor_mask],
               "class_num": class_num, "ignore_thresh": ignore_thresh,
               "downsample_ratio": downsample_ratio,
               "use_label_smooth": use_label_smooth})
    return loss


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None):
    helper = LayerHelper("psroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="psroi_pool", inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"output_channels": output_channels,
               "spatial_scale": spatial_scale, "pooled_height": pooled_height,
               "pooled_width": pooled_width})
    return out


def prroi_pool(input, rois, output_channels=None, spatial_scale=1.0,
               pooled_height=1, pooled_width=1, name=None):
    helper = LayerHelper("prroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="prroi_pool", inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"spatial_scale": float(spatial_scale),
               "pooled_height": pooled_height, "pooled_width": pooled_width})
    return out


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              name=None):
    helper = LayerHelper("roi_perspective_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mask = helper.create_variable_for_type_inference("int32")
    mat = helper.create_variable_for_type_inference(input.dtype)
    o2i = helper.create_variable_for_type_inference("int32")
    o2w = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="roi_perspective_transform",
        inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out], "Mask": [mask], "TransformMatrix": [mat],
                 "Out2InIdx": [o2i], "Out2InWeights": [o2w]},
        attrs={"transformed_height": transformed_height,
               "transformed_width": transformed_width,
               "spatial_scale": spatial_scale})
    return out, mask, mat


def deformable_conv(input, offset, mask, num_filters, filter_size, stride=1,
                    padding=0, dilation=1, groups=None, deformable_groups=None,
                    im2col_step=None, param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    """Deformable conv v2 (modulated=True, layers/nn.py:12714) / v1."""
    helper = LayerHelper("deformable_conv", bias_attr=bias_attr, name=name)
    dtype = input.dtype
    groups = groups or 1
    deformable_groups = deformable_groups or 1
    num_channels = input.shape[1]
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    dilation = ([dilation, dilation] if isinstance(dilation, int)
                else list(dilation))
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)

    import math as _math
    from ..initializer import Normal

    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    w = helper.create_parameter(
        attr=param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=Normal(0.0, _math.sqrt(2.0 / fan_in)))
    out = helper.create_variable_for_type_inference(dtype)
    attrs = {"strides": stride, "paddings": padding, "dilations": dilation,
             "groups": groups, "deformable_groups": deformable_groups,
             "im2col_step": im2col_step or 64}
    if modulated:
        helper.append_op(
            type="deformable_conv",
            inputs={"Input": [input], "Offset": [offset], "Mask": [mask],
                    "Filter": [w]},
            outputs={"Output": [out]}, attrs=attrs)
    else:
        helper.append_op(
            type="deformable_conv_v1",
            inputs={"Input": [input], "Offset": [offset], "Filter": [w]},
            outputs={"Output": [out]}, attrs=attrs)
    return helper.append_bias_op(out, dim_start=1, dim_end=2)


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1,
                           position_sensitive=False, name=None):
    helper = LayerHelper("deformable_roi_pooling", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    top = helper.create_variable_for_type_inference(input.dtype)
    gs = (list(group_size) if not isinstance(group_size, int)
          else [group_size, group_size])
    if len(gs) == 1:
        gs = [gs[0], gs[0]]
    if part_size is None:
        part_size = [pooled_height, pooled_width]
    elif isinstance(part_size, int):
        part_size = [part_size, part_size]
    if position_sensitive:
        output_dim = input.shape[1] // (gs[0] * gs[1])
    else:
        # non-PS mode: treat every channel independently (group 1)
        output_dim = input.shape[1]
        gs = [1, 1]
    helper.append_op(
        type="deformable_psroi_pooling",
        inputs={"Input": [input], "ROIs": [rois], "Trans": [trans]},
        outputs={"Output": [out], "TopCount": [top]},
        attrs={"no_trans": no_trans, "spatial_scale": float(spatial_scale),
               "output_dim": output_dim, "group_size": gs,
               "pooled_height": pooled_height, "pooled_width": pooled_width,
               "part_size": list(part_size),
               "sample_per_part": sample_per_part,
               "trans_std": trans_std})
    return out


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       return_rois_num=False, name=None):
    helper = LayerHelper("generate_proposals", name=name)
    rois = helper.create_variable_for_type_inference(scores.dtype)
    probs = helper.create_variable_for_type_inference(scores.dtype)
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="generate_proposals",
        inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                "ImInfo": [im_info], "Anchors": [anchors],
                "Variances": [variances]},
        outputs={"RpnRois": [rois], "RpnRoiProbs": [probs],
                 "RpnRoisNum": [num]},
        attrs={"pre_nms_topN": pre_nms_top_n, "post_nms_topN": post_nms_top_n,
               "nms_thresh": nms_thresh, "min_size": min_size, "eta": eta})
    if return_rois_num:
        return rois, probs, num
    return rois, probs


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var, gt_boxes,
                      is_crowd, im_info, rpn_batch_size_per_im=256,
                      rpn_straddle_thresh=0.0, rpn_fg_fraction=0.5,
                      rpn_positive_overlap=0.7, rpn_negative_overlap=0.3,
                      use_random=True):
    """detection.py:289.  Static-shape inputs: gt_boxes [N, G, 4] padded,
    is_crowd [N, G] (batch-padded in place of LoD).  `use_random` maps to
    deterministic IoU-priority sampling (see op docstring)."""
    from . import nn as _nn
    from . import tensor as _tensor

    helper = LayerHelper("rpn_target_assign")
    loc_index = helper.create_variable_for_type_inference("int32")
    score_index = helper.create_variable_for_type_inference("int32")
    target_label = helper.create_variable_for_type_inference("int32")
    target_bbox = helper.create_variable_for_type_inference(anchor_box.dtype)
    inside_w = helper.create_variable_for_type_inference(anchor_box.dtype)
    helper.append_op(
        type="rpn_target_assign",
        inputs={"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
                "IsCrowd": [is_crowd], "ImInfo": [im_info]},
        outputs={"LocationIndex": [loc_index], "ScoreIndex": [score_index],
                 "TargetLabel": [target_label], "TargetBBox": [target_bbox],
                 "BBoxInsideWeight": [inside_w]},
        attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
               "rpn_straddle_thresh": rpn_straddle_thresh,
               "rpn_positive_overlap": rpn_positive_overlap,
               "rpn_negative_overlap": rpn_negative_overlap,
               "rpn_fg_fraction": rpn_fg_fraction,
               "use_random": use_random})
    pred_loc = _nn.gather(_nn.reshape(bbox_pred, [-1, 4]),
                          _nn.reshape(loc_index, [-1]))
    pred_score = _nn.gather(_nn.reshape(cls_logits, [-1, 1]),
                            _nn.reshape(score_index, [-1]))
    return pred_score, pred_loc, target_label, target_bbox, inside_w


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd, im_info,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4):
    from . import nn as _nn

    helper = LayerHelper("retinanet_target_assign")
    loc_index = helper.create_variable_for_type_inference("int32")
    score_index = helper.create_variable_for_type_inference("int32")
    target_label = helper.create_variable_for_type_inference("int32")
    target_bbox = helper.create_variable_for_type_inference(anchor_box.dtype)
    inside_w = helper.create_variable_for_type_inference(anchor_box.dtype)
    fg_num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="retinanet_target_assign",
        inputs={"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
                "GtLabels": [gt_labels], "IsCrowd": [is_crowd],
                "ImInfo": [im_info]},
        outputs={"LocationIndex": [loc_index], "ScoreIndex": [score_index],
                 "TargetLabel": [target_label], "TargetBBox": [target_bbox],
                 "BBoxInsideWeight": [inside_w],
                 "ForegroundNumber": [fg_num]},
        attrs={"positive_overlap": positive_overlap,
               "negative_overlap": negative_overlap})
    pred_loc = _nn.gather(_nn.reshape(bbox_pred, [-1, 4]),
                          _nn.reshape(loc_index, [-1]))
    pred_score = _nn.gather(
        _nn.reshape(cls_logits, [-1, num_classes]),
        _nn.reshape(score_index, [-1]))
    return (pred_score, pred_loc, target_label, target_bbox, inside_w,
            fg_num)


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.25, bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    """detection.py:2437.  rpn_rois here is [N, R, 4] per-image (reshape of
    generate_proposals output); gt_* are [N, G, ...] padded."""
    helper = LayerHelper("generate_proposal_labels")
    rois = helper.create_variable_for_type_inference(rpn_rois.dtype)
    labels = helper.create_variable_for_type_inference("int32")
    bbox_targets = helper.create_variable_for_type_inference(rpn_rois.dtype)
    inside_w = helper.create_variable_for_type_inference(rpn_rois.dtype)
    outside_w = helper.create_variable_for_type_inference(rpn_rois.dtype)
    helper.append_op(
        type="generate_proposal_labels",
        inputs={"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
                "IsCrowd": [is_crowd], "GtBoxes": [gt_boxes],
                "ImInfo": [im_info]},
        outputs={"Rois": [rois], "LabelsInt32": [labels],
                 "BboxTargets": [bbox_targets],
                 "BboxInsideWeights": [inside_w],
                 "BboxOutsideWeights": [outside_w]},
        attrs={"batch_size_per_im": batch_size_per_im,
               "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
               "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo,
               "bbox_reg_weights": list(bbox_reg_weights),
               "class_nums": class_nums or 81, "use_random": use_random,
               "is_cls_agnostic": is_cls_agnostic,
               "is_cascade_rcnn": is_cascade_rcnn})
    return rois, labels, bbox_targets, inside_w, outside_w


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution):
    helper = LayerHelper("generate_mask_labels")
    mask_rois = helper.create_variable_for_type_inference(rois.dtype)
    has_mask = helper.create_variable_for_type_inference("int32")
    mask_int32 = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="generate_mask_labels",
        inputs={"ImInfo": [im_info], "GtClasses": [gt_classes],
                "IsCrowd": [is_crowd], "GtSegms": [gt_segms],
                "Rois": [rois], "LabelsInt32": [labels_int32]},
        outputs={"MaskRois": [mask_rois], "RoiHasMaskInt32": [has_mask],
                 "MaskInt32": [mask_int32]},
        attrs={"num_classes": num_classes, "resolution": resolution})
    return mask_rois, has_mask, mask_int32


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    helper = LayerHelper("retinanet_detection_output")
    out = helper.create_variable_for_type_inference(bboxes[0].dtype)
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="retinanet_detection_output",
        inputs={"BBoxes": list(bboxes), "Scores": list(scores),
                "Anchors": list(anchors), "ImInfo": [im_info]},
        outputs={"Out": [out], "OutNum": [num]},
        attrs={"score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
               "nms_eta": nms_eta})
    return out


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                       nms_threshold=0.3, normalized=True, nms_eta=1.0,
                       background_label=-1, name=None):
    helper = LayerHelper("locality_aware_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    helper.append_op(
        type="locality_aware_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out]},
        attrs={"background_label": background_label,
               "score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "nms_threshold": nms_threshold, "nms_eta": nms_eta,
               "keep_top_k": keep_top_k, "normalized": normalized})
    return out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, name=None):
    helper = LayerHelper("distribute_fpn_proposals", name=name)
    n_lvl = max_level - min_level + 1
    outs = [helper.create_variable_for_type_inference(fpn_rois.dtype)
            for _ in range(n_lvl)]
    restore = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="distribute_fpn_proposals", inputs={"FpnRois": [fpn_rois]},
        outputs={"MultiFpnRois": outs, "RestoreIndex": [restore]},
        attrs={"min_level": min_level, "max_level": max_level,
               "refer_level": refer_level, "refer_scale": refer_scale})
    return outs, restore


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, name=None):
    helper = LayerHelper("collect_fpn_proposals", name=name)
    n = max_level - min_level + 1
    out = helper.create_variable_for_type_inference(multi_rois[0].dtype)
    helper.append_op(
        type="collect_fpn_proposals",
        inputs={"MultiLevelRois": list(multi_rois[:n]),
                "MultiLevelScores": list(multi_scores[:n])},
        outputs={"FpnRois": [out]},
        attrs={"post_nms_topN": post_nms_top_n})
    return out


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip, name=None):
    helper = LayerHelper("box_decoder_and_assign", name=name)
    dec = helper.create_variable_for_type_inference(prior_box.dtype)
    assign = helper.create_variable_for_type_inference(prior_box.dtype)
    helper.append_op(
        type="box_decoder_and_assign",
        inputs={"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
                "TargetBox": [target_box], "BoxScore": [box_score]},
        outputs={"DecodeBox": [dec], "OutputAssignBox": [assign]},
        attrs={"box_clip": box_clip})
    return dec, assign


def similarity_focus(input, axis, indexes, name=None):
    helper = LayerHelper("similarity_focus", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="similarity_focus", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"axis": axis, "indexes": list(indexes)})
    return out


def filter_by_instag(ins, ins_tag, filter_tag, is_lod):
    helper = LayerHelper("filter_by_instag")
    out = helper.create_variable_for_type_inference(ins.dtype)
    loss_weight = helper.create_variable_for_type_inference("float32")
    index_map = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="filter_by_instag",
        inputs={"Ins": [ins], "Ins_tag": [ins_tag],
                "Filter_tag": [filter_tag]},
        outputs={"Out": [out], "LossWeight": [loss_weight],
                 "IndexMap": [index_map]},
        attrs={"is_lod": is_lod})
    return out, loss_weight, index_map


def continuous_value_model(input, cvm, use_cvm=True):
    helper = LayerHelper("cvm")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="cvm", inputs={"X": [input], "CVM": [cvm]},
                     outputs={"Y": [out]}, attrs={"use_cvm": use_cvm})
    return out
