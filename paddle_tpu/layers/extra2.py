"""Second batch of API-surface fills: control-flow multiplexers, readers,
sequence extras, detection compositions, misc (parity:
python/paddle/fluid/layers/{control_flow,io,nn,detection,sequence_lod}.py).
"""

import numpy as np

from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "case", "switch_case", "ctc_greedy_decoder", "chunk_eval",
    "detection_output", "image_resize_short", "resize_trilinear",
    "gaussian_random_batch_size_like", "hash", "im2sequence", "lod_append",
    "merge_selected_rows", "get_tensor_from_selected_rows", "unique",
    "tensor_array_to_tensor", "sequence_reshape", "sequence_slice",
    "sequence_scatter", "py_reader", "create_py_reader_by_data",
    "double_buffer", "read_file", "Decoder", "multi_box_head", "ssd_loss",
]


# -- control-flow multiplexers ------------------------------------------------


def case(pred_fn_pairs, default=None, name=None):
    """Multi-branch select (reference layers/control_flow.py case): chained
    layers.cond — the first true predicate's branch wins."""
    from .control_flow import cond

    if not pred_fn_pairs:
        raise ValueError("case: pred_fn_pairs must be non-empty")

    def build(pairs):
        pred, fn = pairs[0]
        if len(pairs) == 1:
            if default is None:
                return cond(pred, fn, fn)
            return cond(pred, fn, default)
        return cond(pred, fn, lambda: build(pairs[1:]))

    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Integer-indexed branch select (reference switch_case)."""
    from . import tensor as T

    from ..layer_helper import LayerHelper as _LH

    def eq(a, b):
        helper = _LH("switch_case_eq")
        out = helper.create_variable_for_type_inference("bool")
        helper.append_op(type="equal", inputs={"X": [a], "Y": [b]},
                         outputs={"Out": [out]})
        return out

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (list, tuple)) \
            and not callable(branch_fns[0]):
        items = sorted((int(i), fn) for i, fn in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    pairs = []
    for idx, fn in items:
        c = T.fill_constant([1], "int64", idx)
        pairs.append((eq(branch_index, c), fn))
    return case(pairs, default=default, name=name)


# -- CTC / chunk metrics ------------------------------------------------------


def ctc_greedy_decoder(input, blank, name=None):
    """Greedy CTC decode (reference ctc_greedy_decoder): argmax per step,
    collapse repeats, drop blanks.  Dense [B, T, C] in, [B, T] out padded
    with -1 (the reference emits ragged LoD)."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="ctc_align", inputs={"Input": [input]},
        outputs={"Output": [out]}, attrs={"blank": blank})
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Chunk counting for NER F1 (reference chunk_eval, IOB scheme):
    returns (precision, recall, f1, num_infer, num_label, num_correct)."""
    helper = LayerHelper("chunk_eval")
    outs = [helper.create_variable_for_type_inference("float32")
            for _ in range(3)]
    counts = [helper.create_variable_for_type_inference("int64")
              for _ in range(3)]
    helper.append_op(
        type="chunk_eval",
        inputs={"Inference": [input], "Label": [label]},
        outputs={"Precision": [outs[0]], "Recall": [outs[1]],
                 "F1-Score": [outs[2]], "NumInferChunks": [counts[0]],
                 "NumLabelChunks": [counts[1]],
                 "NumCorrectChunks": [counts[2]]},
        attrs={"chunk_scheme": chunk_scheme,
               "num_chunk_types": num_chunk_types,
               "excluded_chunk_types": list(excluded_chunk_types or [])})
    return tuple(outs) + tuple(counts)


# -- detection compositions ---------------------------------------------------


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """SSD output: decode loc vs priors then NMS (reference
    detection_output = box_coder + multiclass_nms)."""
    from . import extra as D
    from . import nn

    decoded = D.box_coder(prior_box, prior_box_var, loc,
                          code_type="decode_center_size")
    scores_t = nn.transpose(scores, [0, 2, 1])
    return D.multiclass_nms(decoded, scores_t,
                            score_threshold=score_threshold,
                            nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                            nms_threshold=nms_threshold, nms_eta=nms_eta,
                            background_label=background_label)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD head over multiple feature maps (reference multi_box_head):
    per-map prior boxes + loc/conf convs, concatenated."""
    from . import extra as D
    from . import nn, tensor as T

    n_maps = len(inputs)
    if min_sizes is None:
        # reference ratio schedule
        min_sizes, max_sizes = [], []
        step = int(np.floor((max_ratio - min_ratio) / (n_maps - 2)))
        for r in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i]
        mx = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                            (list, tuple)) else [aspect_ratios[i]]
        box, var = D.prior_box(
            feat, image, min_sizes=[ms], max_sizes=[mx] if mx else None,
            aspect_ratios=ar, variance=variance, flip=flip, clip=clip,
            steps=(steps[i], steps[i]) if steps else (0.0, 0.0),
            offset=offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        box2 = nn.reshape(box, [-1, 4])
        var2 = nn.reshape(var, [-1, 4])
        a = box.shape[2]
        loc = nn.conv2d(feat, a * 4, kernel_size, padding=pad, stride=stride)
        conf = nn.conv2d(feat, a * num_classes, kernel_size, padding=pad,
                         stride=stride)
        loc = nn.reshape(nn.transpose(loc, [0, 2, 3, 1]), [0, -1, 4])
        conf = nn.reshape(nn.transpose(conf, [0, 2, 3, 1]),
                          [0, -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_all.append(box2)
        vars_all.append(var2)
    mbox_locs = T.concat(locs, axis=1)
    mbox_confs = T.concat(confs, axis=1)
    boxes = T.concat(boxes_all, axis=0)
    variances = T.concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mismatch_value=0, normalize=True, sample_size=None):
    """SSD multibox loss (reference ssd_loss, simplified): IoU matching +
    per-prior encoded smooth-L1 loc loss + softmax conf loss.  Hard
    negative mining is replaced by full-negative weighting (all background
    priors contribute to the conf loss) — XLA-friendly static shapes.
    Single-image convention: gt_box [G, 4], gt_label [G], location
    [P, 4] or [1, P, 4], confidence [P, C] or [1, P, C]."""
    from . import extra as D
    from . import nn

    iou = D.iou_similarity(gt_box, prior_box)            # [G, P]
    midx, _ = D.bipartite_match(iou, match_type, overlap_threshold)
    from . import tensor as T

    lbl = nn.reshape(T.cast(gt_label, "float32"), [1, -1, 1])
    tgt_lbl, _ = D.target_assign(lbl, midx,
                                 mismatch_value=background_label)
    tgt_box, box_w = D.target_assign(
        nn.reshape(gt_box, [1, -1, 4]), midx, mismatch_value=0)

    helper = LayerHelper("ssd_loss")
    enc = helper.create_variable_for_type_inference("float32")
    enc_inputs = {"PriorBox": [prior_box],
                  "TargetBox": [nn.reshape(tgt_box, [-1, 4])]}
    enc_attrs = {"variance": []}
    if isinstance(prior_box_var, Variable):
        enc_inputs["PriorBoxVar"] = [prior_box_var]
    elif prior_box_var is not None:
        enc_attrs["variance"] = [float(v) for v in prior_box_var]
    helper.append_op(type="box_encode_paired", inputs=enc_inputs,
                     outputs={"OutputBox": [enc]}, attrs=enc_attrs)

    num_classes = int(confidence.shape[-1])
    loc2 = nn.reshape(location, [-1, 4])
    l1 = nn.smooth_l1(loc2, enc)
    matched = nn.reshape(T.cast(box_w, "float32"), [-1, 1])
    loc_loss = nn.reduce_sum(l1 * matched) * loc_loss_weight
    conf_loss = nn.softmax_with_cross_entropy(
        nn.reshape(confidence, [-1, num_classes]),
        nn.reshape(T.cast(tgt_lbl, "int64"), [-1, 1]))
    conf_loss = nn.reduce_sum(conf_loss) * conf_loss_weight
    total = loc_loss + conf_loss
    if normalize:
        total = total / (nn.reduce_sum(matched) + 1e-6)
    return total


# -- misc ---------------------------------------------------------------------


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    from . import nn

    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    scale = out_short_len / float(short)
    return nn.image_resize(input, out_shape=[int(round(h * scale)),
                                             int(round(w * scale))],
                           resample=resample)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     align_corners=True, align_mode=1, data_format="NCDHW"):
    helper = LayerHelper("resize_trilinear", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="trilinear_interp", inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"out_shape": [int(v) for v in (out_shape or [])],
               "scale": float(scale or 0.0),
               "align_corners": align_corners})
    return out


def gaussian_random_batch_size_like(input, shape, mean=0.0, std=1.0,
                                    input_dim_idx=0, output_dim_idx=0,
                                    dtype="float32"):
    from . import tensor as T
    from . import nn

    base = T.fill_constant_batch_size_like(input, shape, dtype, 0.0,
                                           input_dim_idx=input_dim_idx)
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gaussian_random_like", inputs={"X": [base]},
        outputs={"Out": [out]}, attrs={"mean": float(mean),
                                       "std": float(std)})
    return out


def hash(input, hash_size, num_hash=1, name=None):
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="hash", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"mod_by": hash_size, "num_hash": num_hash})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    helper = LayerHelper("im2sequence", name=name)
    fs = [filter_size] * 2 if isinstance(filter_size, int) else list(filter_size)
    st = [stride] * 2 if isinstance(stride, int) else list(stride)
    pd = [padding] * 2 if isinstance(padding, int) else list(padding)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="im2sequence", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"kernels": fs, "strides": st, "paddings": pd})
    return out


def lod_append(x, level):
    """LoD metadata append — dense tensors carry lod only as metadata."""
    return x


def merge_selected_rows(x, name=None):
    """SelectedRows dissolve into dense on XLA: identity."""
    return x


def get_tensor_from_selected_rows(x, name=None):
    return x


def unique(x, dtype="int32"):
    from . import extra as E

    out, idx, _ = E.unique_with_counts(x, dtype)
    return out, idx


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    """Concat/stack a tensor array (reference tensor_array_to_tensor)."""
    from . import tensor as T

    if isinstance(input, (list, tuple)):
        arrs = list(input)
        if use_stack:
            out = T.stack(arrs, axis=0)
            sizes = [1] * len(arrs)
        else:
            out = T.concat(arrs, axis=axis)
            sizes = [int(a.shape[axis]) for a in arrs]
        idx = T.assign(np.asarray(sizes, "int32")) if hasattr(T, "assign") \
            else T.fill_constant([len(arrs)], "int32", sizes[0])
        return out, idx
    raise NotImplementedError(
        "tensor_array_to_tensor on a runtime LoDTensorArray requires the "
        "array ops path; pass a Python list of Variables")


# -- sequence extras (dense/padded semantics) --------------------------------


def sequence_reshape(input, new_dim):
    from . import nn

    return nn.reshape(input, [0, -1, new_dim]) if len(input.shape) == 3 \
        else nn.reshape(input, [-1, new_dim])


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_slice_dense",
        inputs={"X": [input], "Offset": [offset], "Length": [length]},
        outputs={"Out": [out]})
    return out


def sequence_scatter(input, index, updates, name=None):
    from . import nn

    return nn.scatter(input, index, updates)


# -- reader aliases -----------------------------------------------------------


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Static py_reader (reference layers/io.py py_reader): returns a
    PyReader-like object whose decorate/start/reset drive the program's
    attached-loader feed path."""
    from .. import data as _data
    from ..reader import PyReader

    feed_list = [
        _data("_py_reader_in_%d" % i, shape=list(s)[1:], dtype=d)
        for i, (s, d) in enumerate(zip(shapes, dtypes))]
    r = PyReader(feed_list=feed_list, capacity=capacity,
                 use_double_buffer=use_double_buffer, iterable=False)
    r.read_vars = feed_list
    return r


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    from ..reader import PyReader

    r = PyReader(feed_list=feed_list, capacity=capacity,
                 use_double_buffer=use_double_buffer, iterable=False)
    r.read_vars = feed_list
    return r


def double_buffer(reader, place=None, name=None):
    return reader  # buffering handled inside the native queue pipeline


def read_file(reader):
    """Pull the next batch's variables from a started reader."""
    if hasattr(reader, "read_vars"):
        return reader.read_vars
    raise ValueError("read_file expects a py_reader-created reader")


class Decoder:
    """Decode-step protocol for dynamic_decode (reference layers/rnn.py
    Decoder): implement initialize(inits) -> (inputs, states, finished) and
    step(time, inputs, states) -> (outputs, states, next_inputs, finished)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError
