"""Blockwise (flash) attention as Pallas TPU kernels.

TPU-native replacement for the reference's fused attention
(paddle/fluid/operators/fused/multihead_matmul_op.cu — inference-only,
single-device) with training support: an online-softmax forward that never
materializes the [Sq, Sk] score matrix in HBM, plus recompute-based backward
kernels for dQ and dK/dV (FlashAttention-style).  Everything is tiled to the
MXU (128-lane blocks), accumulated in f32 VMEM scratch, and differentiable
via jax.custom_vjp.

Layout: q, k, v are [batch, heads, seq, head_dim]; optional additive bias
(attention mask) is [batch, 1 or heads, Sq, Sk].  Outside TPU (or for shapes
the tiling cannot cover) a jnp reference path with identical semantics is
used, so tests run on the CPU mesh unchanged.
"""

import functools

import jax
import jax.numpy as jnp

from . import prng as _prng

__all__ = ["flash_attention"]

_NEG_INF = -1e30
_LANES = 128


def _ref_attention(q, k, v, bias, causal, sm_scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        qi = jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 0)
        kj = jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 1)
        s = jnp.where(kj <= qi, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, sm_scale, causal, block_q, block_k):
    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # k block
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: k blocks strictly above the diagonal contribute nothing
    needed = True
    if causal:
        needed = j * block_k <= i * block_q + block_q - 1

    @pl.when(needed)
    def _body():
        qb = q_ref[0, 0].astype(jnp.float32)
        kb = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)

        m_prev = m_scr[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v_ref[0, 0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        # NB: rows masked everywhere (finite -1e30 bias) degenerate to a
        # uniform softmax (row max contributes p=1, so l >= 1) — output is
        # mean(V), matching the jnp fallback's softmax-over--inf behavior;
        # the l==0 guard is pure belt-and-braces against future NaN masks
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = m_scr[:, :1] + jnp.log(l_safe)
        lse_ref[0, 0] = lse


# ---------------------------------------------------------------------------
# backward kernels (FlashAttention recompute scheme)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr, *, sm_scale, causal, block_q, block_k):
    i = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    needed = True
    if causal:
        needed = j * block_k <= i * block_q + block_q - 1

    @pl.when(needed)
    def _body():
        qb = q_ref[0, 0].astype(jnp.float32)
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        dob = do_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0])
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0]) * sm_scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale, causal,
                    block_q, block_k):
    j = pl.program_id(2)  # k block (outer)
    i = pl.program_id(3)  # q block (inner, accumulated)
    nq = pl.num_programs(3)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    needed = True
    if causal:
        needed = j * block_k <= i * block_q + block_q - 1

    @pl.when(needed)
    def _body():
        qb = q_ref[0, 0].astype(jnp.float32)
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        dob = do_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0])
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0]) * sm_scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # renamed TPUCompilerParams -> CompilerParams across jax releases
    _CompilerParams = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False


def _causal_jmax(i, block_q, block_k):
    """Last k-block index that intersects q-block i's causal band."""
    return (i * block_q + block_q - 1) // block_k


def _causal_imin(j, block_q, block_k):
    """First q-block index that intersects k-block j's causal band."""
    return (j * block_k) // block_q


def _bias_spec(bias, block_q, block_k, causal):
    if bias is None:
        return None
    bh = bias.shape[1]

    def idx(b, h, i, j):
        if causal:
            j = jnp.minimum(j, _causal_jmax(i, block_q, block_k))
        return (b, h if bh > 1 else 0, i, j)

    return pl.BlockSpec((1, 1, block_q, block_k), idx)


def _bias_spec_ji(bias, block_q, block_k, causal):
    if bias is None:
        return None
    bh = bias.shape[1]

    def idx(b, h, j, i):
        if causal:
            i = jnp.maximum(i, _causal_imin(j, block_q, block_k))
        return (b, h if bh > 1 else 0, i, j)

    return pl.BlockSpec((1, 1, block_q, block_k), idx)


def _pick_block(seq, preferred=512):
    for cand in (preferred, 512, 256, 128):
        if cand <= seq and seq % cand == 0:
            return cand
    return None


def _fwd_pallas(q, k, v, bias, causal, sm_scale, block_q, block_k,
                interpret):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    nq, nk = Sq // block_q, Sk // block_k
    grid = (B, H, nq, nk)

    def kv_idx(b, h, i, j):
        # clamping the block index to the causal band makes Pallas's
        # pipeline reuse the previous buffer instead of fetching dead
        # above-diagonal K/V blocks
        if causal:
            j = jnp.minimum(j, _causal_jmax(i, block_q, block_k))
        return (b, h, j, 0)

    in_specs = [
        pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, block_k, D), kv_idx),
        pl.BlockSpec((1, 1, block_k, D), kv_idx),
    ]
    args = [q, k, v]
    bspec = _bias_spec(bias, block_q, block_k, causal)
    if bias is not None:
        in_specs.append(bspec)
        args.append(bias)

    if bias is not None:
        def kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr):
            _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                        m_scr, l_scr, acc_scr, sm_scale=sm_scale,
                        causal=causal, block_q=block_q, block_k=block_k)
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr):
            _fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                        m_scr, l_scr, acc_scr, sm_scale=sm_scale,
                        causal=causal, block_q=block_q, block_k=block_k)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, H, Sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*args)
    return out, lse


def _bwd_pallas(q, k, v, bias, causal, sm_scale, block_q, block_k,
                interpret, out, lse, do):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    nq, nk = Sq // block_q, Sk // block_k

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B,H,Sq,1]

    common = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
                  block_k=block_k)

    # dq: grid (B,H,nq,nk), k-inner
    def kv_idx(b, h, i, j):
        if causal:
            j = jnp.minimum(j, _causal_jmax(i, block_q, block_k))
        return (b, h, j, 0)

    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    k_spec = pl.BlockSpec((1, 1, block_k, D), kv_idx)
    row_spec = pl.BlockSpec((1, 1, block_q, 1),
                            lambda b, h, i, j: (b, h, i, 0))
    in_specs = [q_spec, k_spec, k_spec]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(_bias_spec(bias, block_q, block_k, causal))
        args.append(bias)
    in_specs += [q_spec, row_spec, row_spec]
    args += [do, lse, delta]

    if bias is not None:
        def dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                      delta_ref, dq_ref, dq_scr):
            _bwd_dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                           delta_ref, dq_ref, dq_scr, **common)
    else:
        def dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dq_ref, dq_scr):
            _bwd_dq_kernel(q_ref, k_ref, v_ref, None, do_ref, lse_ref,
                           delta_ref, dq_ref, dq_scr, **common)

    dq = pl.pallas_call(
        dq_kernel,
        grid=(B, H, nq, nk),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*args)

    # dk/dv: grid (B,H,nk,nq), q-inner
    def qrow_i(j, i):
        if causal:
            i = jnp.maximum(i, _causal_imin(j, block_q, block_k))
        return i

    q_spec_ji = pl.BlockSpec((1, 1, block_q, D),
                             lambda b, h, j, i: (b, h, qrow_i(j, i), 0))
    k_spec_ji = pl.BlockSpec((1, 1, block_k, D),
                             lambda b, h, j, i: (b, h, j, 0))
    row_spec_ji = pl.BlockSpec((1, 1, block_q, 1),
                               lambda b, h, j, i: (b, h, qrow_i(j, i), 0))
    in_specs = [q_spec_ji, k_spec_ji, k_spec_ji]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(_bias_spec_ji(bias, block_q, block_k, causal))
        args.append(bias)
    in_specs += [q_spec_ji, row_spec_ji, row_spec_ji]
    args += [do, lse, delta]

    if bias is not None:
        def dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                       delta_ref, dk_ref, dv_ref, dk_scr, dv_scr):
            _bwd_dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                            delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                            **common)
    else:
        def dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                       delta_ref, dk_ref, dv_ref, dk_scr, dv_scr):
            _bwd_dkv_kernel(q_ref, k_ref, v_ref, None, do_ref, lse_ref,
                            delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                            **common)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B, H, nk, nq),
        in_specs=in_specs,
        out_specs=[k_spec_ji, k_spec_ji],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def _can_use_pallas(q, k, interpret):
    if not _HAS_PALLAS:
        return False, None, None
    Sq, Sk = q.shape[2], k.shape[2]
    if Sk < 1024:
        # measured on v5e: below ~1k keys the XLA-fused composition is
        # faster (kernel launch/grid overhead dominates); above it the
        # blockwise kernel wins and, more importantly, never materializes
        # the [Sq, Sk] score matrix
        return False, None, None
    bq = _pick_block(Sq, preferred=1024 if Sq >= 4096 else 512)
    bk = _pick_block(Sk, preferred=1024)
    if bq is None or bk is None:
        return False, None, None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
        if interpret:
            return False, None, None  # CPU: jnp reference is faster than interpret
    return True, (bq, bk), interpret


# bias=None routes through the same vjp (None is a valid empty pytree for a
# differentiable argument; bwd returns None for its cotangent)
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_b(q, k, v, bias, causal, sm_scale, blocks, interpret):
    out, _ = _fwd_pallas(q, k, v, bias, causal, sm_scale, blocks[0],
                         blocks[1], interpret)
    return out


def _flash_b_fwd(q, k, v, bias, causal, sm_scale, blocks, interpret):
    out, lse = _fwd_pallas(q, k, v, bias, causal, sm_scale, blocks[0],
                           blocks[1], interpret)
    return out, (q, k, v, bias, out, lse)


def _flash_b_bwd(causal, sm_scale, blocks, interpret, res, do):
    q, k, v, bias, out, lse = res
    dq, dk, dv = _bwd_pallas(q, k, v, bias, causal, sm_scale, blocks[0],
                             blocks[1], interpret, out, lse, do)
    return dq, dk, dv, None


_flash_b.defvjp(_flash_b_fwd, _flash_b_bwd)


# ---------------------------------------------------------------------------
# small-sequence fused training attention (mask + dropout INSIDE the kernel)
# ---------------------------------------------------------------------------
#
# The blockwise kernel above targets long sequences (online softmax, never
# materializes [Sq, Sk]).  At the flagship BERT training shape
# (bs256/seq128) the whole score tile fits in VMEM, so this kernel does
# plain softmax per head and — the actual point — draws the
# attention-prob dropout mask with the ON-CORE PRNG: the composed
# emission materializes [B, H, S, S] probs (100 MB bf16) plus a u8 keep
# mask (50 MB) per layer and re-reads them in the backward; here neither
# ever touches HBM (the backward re-draws the mask from the 2-word seed
# and recomputes probs from the saved per-row LSE, FlashAttention-style).
# Reference analog: fused/multihead_matmul_op.cu (inference-only there;
# trainable here).

_SMALL_SEQ_MAX = 256


def small_attention_shapes_ok(q_shape, k_shape, bias_shape, causal, layout):
    """Static predicate shared by the op's forward and grad lowerings —
    BOTH must route identically or the backward replays a wrong mask."""
    if layout != "BHSD" or causal:
        return False
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    B, H, S, D = q_shape
    if not all(isinstance(d, int) for d in (B, H, S, D)):
        return False
    if k_shape[2] != S or k_shape[3] != D or S > _SMALL_SEQ_MAX \
            or S % 128 != 0:
        return False
    if D not in (64, 128):
        return False
    if bias_shape is not None:
        # the kernel tiles the bias as full [Sq, Sk] blocks: broadcast
        # shapes like [B,1,1,S] must take the composed fallback
        if (len(bias_shape) != 4 or bias_shape[1] not in (1, H)
                or bias_shape[2] != S or bias_shape[3] != S):
            return False
    return True


def _small_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref,
                      lse_ref, *, sm_scale, thr, H, S, D, bias_per_head):
    if thr is not None:
        _prng.seed_block_prng(seed_ref)
        bits = _prng.draw_keep_bits((H * S, S), thr)
        inv_q = _prng.inv_realized_q(thr)
    for h in range(H):
        # dots take the NATIVE (bf16) operands — the MXU multiplies bf16
        # at full rate and accumulates f32; upcasting operands first
        # makes every dot a multi-pass f32 matmul (~6x slower)
        s = jax.lax.dot_general(q_ref[0, h], k_ref[0, h],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if bias_ref is not None:
            s = s + bias_ref[0, h if bias_per_head else 0].astype(jnp.float32)
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=1, keepdims=True)
        prob = p / l
        if thr is not None:
            keep = bits[h * S:(h + 1) * S, :]
            prob = jnp.where(keep, prob * inv_q, 0.0)
        oh = jax.lax.dot_general(
            prob.astype(v_ref.dtype), v_ref[0, h],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        o_ref[0, h] = oh.astype(o_ref.dtype)
        lse_ref[0, h] = m + jnp.log(l)


def _small_bwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref,
                      lse_ref, delta_ref, dq_ref, dk_ref, dv_ref, *,
                      sm_scale, thr, H, S, D, bias_per_head):
    if thr is not None:
        _prng.seed_block_prng(seed_ref)
        bits = _prng.draw_keep_bits((H * S, S), thr)
        inv_q = _prng.inv_realized_q(thr)
    cdt = q_ref.dtype  # carry dtype for MXU operands (bf16 in training)
    for h in range(H):
        # all dots on native-dtype operands, f32 accumulation (see fwd)
        s = jax.lax.dot_general(q_ref[0, h], k_ref[0, h],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if bias_ref is not None:
            s = s + bias_ref[0, h if bias_per_head else 0].astype(jnp.float32)
        prob = jnp.exp(s - lse_ref[0, h])
        if thr is not None:
            keep = bits[h * S:(h + 1) * S, :]
            pd = jnp.where(keep, prob * inv_q, 0.0)
        else:
            pd = prob
        dv_ref[0, h] = jax.lax.dot_general(
            pd.astype(cdt), do_ref[0, h], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dpd = jax.lax.dot_general(do_ref[0, h], v_ref[0, h],
                                  (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        if thr is not None:
            dp = jnp.where(keep, dpd * inv_q, 0.0)
        else:
            dp = dpd
        ds = (prob * (dp - delta_ref[0, h]) * sm_scale).astype(cdt)
        dq_ref[0, h] = jax.lax.dot_general(
            ds, k_ref[0, h], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        dk_ref[0, h] = jax.lax.dot_general(
            ds, q_ref[0, h], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)


# shared realized-keep-probability contract (pallas_kernels/prng.py)
small_keep_threshold = _prng.keep_threshold


def _small_specs(B, H, S, D, bias_shape):
    qspec = pl.BlockSpec((1, H, S, D), lambda b, *_: (b, 0, 0, 0))
    lspec = pl.BlockSpec((1, H, S, 1), lambda b, *_: (b, 0, 0, 0))
    bias_per_head = bias_shape is not None and bias_shape[1] != 1
    bspec = None
    if bias_shape is not None:
        bh = H if bias_per_head else 1
        bspec = pl.BlockSpec((1, bh, S, S), lambda b, *_: (b, 0, 0, 0))
    return qspec, lspec, bspec, bias_per_head


def small_attention_fwd(q, k, v, bias, sm_scale, dropout_prob, seed):
    """Fused small-seq attention forward: (out, lse).  seed: [2] u32."""
    B, H, S, D = q.shape
    thr = small_keep_threshold(dropout_prob)
    seed = jnp.asarray(seed).reshape(2).astype(jnp.uint32)
    qspec, lspec, bspec, bph = _small_specs(B, H, S, D,
                                            None if bias is None
                                            else bias.shape)
    args = [q, k, v] + ([bias] if bias is not None else [])
    in_specs = [qspec, qspec, qspec] + ([bspec] if bias is not None else [])

    if bias is not None:
        def kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref):
            _small_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref,
                              o_ref, lse_ref, sm_scale=sm_scale, thr=thr,
                              H=H, S=S, D=D, bias_per_head=bph)
    else:
        def kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref):
            _small_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, None,
                              o_ref, lse_ref, sm_scale=sm_scale, thr=thr,
                              H=H, S=S, D=D, bias_per_head=bph)

    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(B,),
            in_specs=in_specs, out_specs=[qspec, lspec]),
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
    )(seed, *args)
    return out, lse


def small_attention_bwd(q, k, v, bias, sm_scale, dropout_prob, seed, out,
                        lse, do):
    """Fused small-seq attention backward: (dq, dk, dv)."""
    B, H, S, D = q.shape
    thr = small_keep_threshold(dropout_prob)
    seed = jnp.asarray(seed).reshape(2).astype(jnp.uint32)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # rowsum(dO . O) — dropout-safe
    qspec, lspec, bspec, bph = _small_specs(B, H, S, D,
                                            None if bias is None
                                            else bias.shape)
    args = [q, k, v] + ([bias] if bias is not None else []) + [do, lse,
                                                               delta]
    in_specs = ([qspec, qspec, qspec]
                + ([bspec] if bias is not None else [])
                + [qspec, lspec, lspec])

    if bias is not None:
        def kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dk_ref, dv_ref):
            _small_bwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref,
                              do_ref, lse_ref, delta_ref, dq_ref, dk_ref,
                              dv_ref, sm_scale=sm_scale, thr=thr, H=H, S=S,
                              D=D, bias_per_head=bph)
    else:
        def kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dk_ref, dv_ref):
            _small_bwd_kernel(seed_ref, q_ref, k_ref, v_ref, None,
                              do_ref, lse_ref, delta_ref, dq_ref, dk_ref,
                              dv_ref, sm_scale=sm_scale, thr=thr, H=H, S=S,
                              D=D, bias_per_head=bph)

    dq, dk, dv = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(B,),
            in_specs=in_specs, out_specs=[qspec, qspec, qspec]),
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
    )(seed, *args)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def small_attention(q, k, v, bias, sm_scale, dropout_prob, seed):
    """Functional fused small-seq training attention (custom vjp).

    softmax(q k^T * scale + bias) with attention-prob dropout
    (upscale_in_train, realized keep prob round(q*2^32)/2^32) applied
    INSIDE the kernel; the backward re-draws the identical mask from
    seed.  TPU-only (callers gate on small_attention_shapes_ok +
    backend)."""
    out, _ = small_attention_fwd(q, k, v, bias, sm_scale, dropout_prob,
                                 seed)
    return out


def _small_attn_vjp_fwd(q, k, v, bias, sm_scale, dropout_prob, seed):
    out, lse = small_attention_fwd(q, k, v, bias, sm_scale, dropout_prob,
                                   seed)
    return out, (q, k, v, bias, seed, out, lse)


def _small_attn_vjp_bwd(sm_scale, dropout_prob, res, do):
    q, k, v, bias, seed, out, lse = res
    dq, dk, dv = small_attention_bwd(q, k, v, bias, sm_scale, dropout_prob,
                                     seed, out, lse, do)
    return dq, dk, dv, None, None


small_attention.defvjp(_small_attn_vjp_fwd, _small_attn_vjp_bwd)


def flash_attention(q, k, v, bias=None, causal=False, sm_scale=None,
                    interpret=None):
    """Fused multi-head attention: softmax(q k^T * scale + bias) v.

    q,k,v: [B, H, S, D]; bias: [B, 1|H, Sq, Sk] additive mask or None.
    Uses the Pallas TPU kernel when on TPU with tileable shapes; falls back
    to an identical-semantics jnp composition otherwise (so the same model
    code runs on the CPU test mesh).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    ok, blocks, interp = _can_use_pallas(q, k, interpret)
    if not ok:
        return _ref_attention(q, k, v, bias, causal, sm_scale)
    return _flash_b(q, k, v, bias, causal, sm_scale, blocks, interp)
