"""Fused LayerNorm forward (Pallas TPU kernel).

The XLA composition reads x for the statistics pass and again for the
normalize pass; this kernel does both in one VMEM-resident pass per row
block — measured 5.44 vs 6.27 ms at BERT-base shapes ([32768, 768] bf16)
on the bench chip, and MORE accurate than the bf16-carry composition
(f32 internal stats: max err 0.015 vs 0.040 against an f64 golden).
In-program it measured -1.5% on full BERT (it breaks XLA's LN-neighbor
fusions), so it ships opt-in: FLAGS_use_pallas_layer_norm.

The backward is a single fused jnp pass (XLA reads x/dy once) using the
saved mean/variance, INCLUDING the mean/variance cotangent contributions
so gradients agree exactly with the differentiable jnp composition.
"""

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, m_ref, v_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    m = jnp.mean(x, axis=1, keepdims=True)
    xc = x - m
    v = jnp.mean(xc * xc, axis=1, keepdims=True)
    y = xc * jax.lax.rsqrt(v + eps)
    y = y * g_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    # stats as [block_r, 1]: 1-D outputs hit XLA/Mosaic tiled-layout
    # mismatches (T(1024) vs T(512)); VARIANCE is emitted directly — the
    # 1/(rstd*rstd)-eps reconstruction catastrophically cancels for
    # near-constant rows and could go negative
    m_ref[...] = m
    v_ref[...] = v


def _pick_block_r(R):
    for b in (512, 256, 128, 64, 32, 16, 8):
        if R % b == 0:
            return b
    return None


def ln_checks(R, C):
    """Ordered (reason, ok) eligibility pairs for adoption.decide() — the
    shared funnel that replaced this module's private copy of the gate
    (fused_ln.py carried a near-duplicate; both now feed adoption.py so a
    fallback is a counted event, not a silent branch)."""
    return [
        ("no_pallas", _HAS_PALLAS),
        ("backend", jax.default_backend() == "tpu"),
        ("lanes", C % 128 == 0),
        ("block_rows", _pick_block_r(R) is not None),
    ]


def can_use_pallas_ln(R, C):
    """Pure eligibility (no flag/probe/telemetry) — tests use this to
    assert the kernel would engage for a shape."""
    return all(ok for _, ok in ln_checks(R, C))


def _fwd_pallas(x, g, b, eps):
    R, C = x.shape
    block_r = _pick_block_r(R)
    y, mean, var = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(R // block_r,),
        in_specs=[pl.BlockSpec((block_r, C), lambda i: (i, 0)),
                  pl.BlockSpec((C,), lambda i: (0,)),
                  pl.BlockSpec((C,), lambda i: (0,))],
        out_specs=[pl.BlockSpec((block_r, C), lambda i: (i, 0)),
                   pl.BlockSpec((block_r, 1), lambda i: (i, 0)),
                   pl.BlockSpec((block_r, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((R, C), x.dtype),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32)],
    )(x, g, b)
    return y, mean[:, 0], var[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layer_norm_2d(x, g, b, eps=1e-5):
    """LN over the last dim: x [R, C], g/b [C] ->
    (y [R, C], mean [R] f32, var [R] f32)."""
    return _fwd_pallas(x, g, b, eps)


def _ln_fwd(x, g, b, eps):
    y, mean, var = _fwd_pallas(x, g, b, eps)
    return (y, mean, var), (x, g, b, mean, var)


def _ln_bwd(eps, res, cts):
    dy, dmean, dvar = cts
    x, g, b, mean, var = res
    C = x.shape[1]
    rstd = jax.lax.rsqrt(var + eps)
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    xhat = (xf - mean[:, None]) * rstd[:, None]
    dyg = dyf * gf[None, :]
    s1 = jnp.sum(dyg, axis=1, keepdims=True)
    s2 = jnp.sum(dyg * xhat, axis=1, keepdims=True)
    dx = (rstd[:, None] / C) * (C * dyg - s1 - xhat * s2)
    # mean/variance cotangents: the jnp composition is differentiable
    # through its Mean/Variance outputs, so the kernel path must agree —
    # d mean/d x = 1/C; d var/d x = 2 (x - mean)/C
    if dmean is not None:
        dx = dx + dmean.astype(jnp.float32)[:, None] / C
    if dvar is not None:
        dx = dx + (2.0 / C) * dvar.astype(jnp.float32)[:, None] * (
            xf - mean[:, None])
    dg = jnp.sum(dyf * xhat, axis=0)
    db = jnp.sum(dyf, axis=0)
    return dx.astype(x.dtype), dg.astype(g.dtype), db.astype(b.dtype)


layer_norm_2d.defvjp(_ln_fwd, _ln_bwd)
