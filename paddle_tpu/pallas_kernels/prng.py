"""Shared in-kernel PRNG / dropout-quantization helpers.

The realized-keep-probability contract — keep iff random_u32 <
round(q * 2^32), upscale divided by that REALIZED probability — is
load-bearing for forward/backward mask replay in BOTH fused kernels
(fused_ln.py, flash_attention.py small_attention_*).  It lives here once
so the copies cannot drift.
"""

import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False

_TWO32 = 1 << 32


def keep_threshold(dropout_prob):
    """u32 compare threshold for the keep draw; None = no dropout.
    Clamped to >= 1 so the degenerate draw cannot divide by zero."""
    q = 1.0 - float(dropout_prob)
    thr = int(round(q * _TWO32))
    if thr >= _TWO32:
        return None
    return max(thr, 1)


def realized_q(thr):
    """The keep probability the threshold actually samples with."""
    return thr / _TWO32


def inv_realized_q(thr):
    """Upscale multiplier 1/realized_q(thr)."""
    return 1.0 / realized_q(thr)


def seed_block_prng(seed_ref, grid_axis=0):
    """Seed the on-core PRNG for the current grid block.

    Mosaic caps prng_seed at 2 words, so the block index folds into word
    0 with a Knuth multiplicative hash — every block draws an
    independent stream, and a backward kernel that calls this with the
    SAME seed words and grid blocking replays the forward's stream
    exactly."""
    pid = pl.program_id(grid_axis).astype(jnp.uint32) * jnp.uint32(
        2654435761)
    pltpu.prng_seed(seed_ref[0] ^ pid, seed_ref[1])


def draw_keep_bits(shape, thr):
    """Draw `shape` keep decisions from the seeded on-core PRNG."""
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    return bits < jnp.uint32(thr)
