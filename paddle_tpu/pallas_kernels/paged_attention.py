"""Paged-attention gather kernel for the decode serving path.

The decode step (serving/decode_model.py) attends one query token per
sequence against that sequence's KV history, which lives scattered across
fixed-size cache blocks (serving/kv_cache.py) named by a per-sequence
block table.  The generic lowering gathers the blocks into a contiguous
``[B, S, H, D]`` intermediate (``jnp.take`` over the block axis) and runs
masked attention over it — B*S*H*D of HBM writes + reads that exist only
to be reduced.  This kernel uses the scalar-prefetched block table to
steer the K/V block DMA directly (the embedding-bag idiom): grid step
(b, j) fetches ONE ``(block_size, H, D)`` K block and V block chosen by
``block_tables[b, j]`` and folds them into an online-softmax accumulator
in VMEM, so the gathered intermediate never materializes.

Positions at or beyond ``context_lens[b]`` are masked with a large
negative before the softmax (finite, so a fully-masked idle lane yields a
uniform distribution instead of NaN — the engine discards idle-lane
output anyway).  ``masked_attention`` is the shared jnp core: the paged
reference gathers blocks and calls it, and the UNPAGED reference loop in
decode_model.py calls the very same function on contiguous K/V — that
sharing is what makes paged-vs-unpaged decode bitwise-comparable on the
CPU tier.

Adoption: FLAGS_use_pallas_paged_attention + ``paged_attention_checks``
eligibility + a >= 1.1x tools/probes row, all through adoption.decide()
(interpret mode waives backend + probe for the CPU parity tests).
"""

import functools
import math

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # renamed TPUCompilerParams -> CompilerParams across jax releases
    _CompilerParams = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False

from . import adoption

__all__ = ["paged_attention", "paged_attention_reference",
           "paged_attention_checks", "masked_attention"]

_MASK = -1e30  # finite: a fully-masked lane softmaxes to uniform, not NaN


def masked_attention(q, k, v, context_lens):
    """Single-token attention over a contiguous history: q [B, H, D],
    k/v [B, S, H, D], context_lens [B] -> [B, H, D].  Positions >= the
    context length are masked.  Shared by the paged gather path AND the
    unpaged reference loop so the two stay bitwise-comparable."""
    d = q.shape[-1]
    s = jnp.einsum("bhd,bshd->bhs", q, k) * (1.0 / math.sqrt(d))
    pos = jnp.arange(k.shape[1], dtype=jnp.int32)[None, None, :]
    s = jnp.where(pos < context_lens[:, None, None].astype(jnp.int32),
                  s, _MASK)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhs,bshd->bhd", p, v)


def paged_attention_reference(q, k_cache, v_cache, block_tables,
                              context_lens):
    """jnp fallback: gather the table's blocks into contiguous K/V, then
    masked_attention.  q [B, H, D]; k_cache/v_cache
    [num_blocks, block_size, H, D]; block_tables [B, MAXB] (entries < 0
    are unused slots, clamped to block 0 and masked by context_lens)."""
    bb, maxb = block_tables.shape
    bs, h, d = k_cache.shape[1:]
    idx = jnp.maximum(block_tables, 0)
    k = jnp.take(k_cache, idx, axis=0).reshape(bb, maxb * bs, h, d)
    v = jnp.take(v_cache, idx, axis=0).reshape(bb, maxb * bs, h, d)
    return masked_attention(q, k, v, context_lens)


def paged_attention_checks(q_shape, kv_shape, dtype, block_size):
    """Ordered (reason, ok) pairs for adoption.decide()."""
    dims = tuple(q_shape) + tuple(kv_shape)
    static = all(isinstance(x, int) and x >= 0 for x in dims)
    return [
        ("no_pallas", _HAS_PALLAS),
        ("backend", adoption.interpret_mode()
         or jax.default_backend() == "tpu"),
        ("symbolic_shape", static),
        ("rank", len(q_shape) == 3 and len(kv_shape) == 4),
        ("dtype", jnp.dtype(dtype) == jnp.dtype(jnp.float32)),
        ("head_dim", static and len(q_shape) == 3
         and q_shape[2] % 128 == 0),
        ("block_size", isinstance(block_size, int) and block_size > 0
         and block_size % 8 == 0),
        ("empty", static and all(x > 0 for x in dims)),
    ]


def _interp():
    return adoption.interpret_mode() or jax.default_backend() != "tpu"


def _paged_kernel(bt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _zero():
        m_ref[...] = jnp.full_like(m_ref, _MASK)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    b = pl.program_id(0)
    bs = k_ref.shape[1]
    scale = 1.0 / math.sqrt(q_ref.shape[-1])
    q = q_ref[0].astype(jnp.float32)                    # [H, D]
    k = k_ref[0].astype(jnp.float32)                    # [bs, H, D]
    s = jnp.einsum("hd,shd->hs", q, k) * scale          # [H, bs]
    pos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
    s = jnp.where(pos < cl_ref[b], s, _MASK)
    # online softmax across the block-table axis (j is sequential)
    m_prev = m_ref[...]                                 # [H, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                              # [H, bs]
    l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jnp.einsum(
        "hs,shd->hd", p, v_ref[0].astype(jnp.float32))
    m_ref[...] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _flush():
        o_ref[0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def _paged_pallas(q, k_cache, v_cache, block_tables, context_lens):
    bb, h, d = q.shape
    bs = k_cache.shape[1]
    maxb = block_tables.shape[1]
    # the prefetched table steers the K/V block DMA; unused (-1) slots
    # clamp to block 0 and are masked off by context_lens in the kernel
    kv_spec = pl.BlockSpec(
        (1, bs, h, d),
        lambda b, j, bt_ref, cl_ref: (jnp.maximum(bt_ref[b, j], 0), 0, 0, 0))
    q_spec = pl.BlockSpec((1, h, d), lambda b, j, bt_ref, cl_ref: (b, 0, 0))
    o_spec = pl.BlockSpec((1, h, d), lambda b, j, bt_ref, cl_ref: (b, 0, 0))
    call = functools.partial(
        pl.pallas_call,
        _paged_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bb, maxb),
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=o_spec,
            scratch_shapes=[pltpu.VMEM((h, 1), jnp.float32),
                            pltpu.VMEM((h, 1), jnp.float32),
                            pltpu.VMEM((h, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bb, h, d), q.dtype),
        interpret=_interp(),
    )
    if not _interp():
        # j accumulates the online softmax, so it must run sequentially
        call = functools.partial(
            call, compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "arbitrary")))
    return call()(block_tables.astype(jnp.int32),
                  context_lens.astype(jnp.int32), q, k_cache, v_cache)


def paged_attention(q, k_cache, v_cache, block_tables, context_lens):
    """Funnel-gated paged attention: the Pallas gather kernel where
    adoption.decide() allows it, the jnp gather reference otherwise."""
    use, _reason = adoption.decide(
        "paged_attention",
        flag="FLAGS_use_pallas_paged_attention",
        checks=paged_attention_checks(q.shape, k_cache.shape, q.dtype,
                                      int(k_cache.shape[1])))
    if use:
        return _paged_pallas(q, k_cache, v_cache, block_tables,
                             context_lens)
    return paged_attention_reference(q, k_cache, v_cache, block_tables,
                                     context_lens)
