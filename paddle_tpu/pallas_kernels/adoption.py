"""Probe-gated Pallas kernel adoption — one funnel for every kernel family.

Five kernel families live under ``pallas_kernels/`` (layer_norm, fused_ln,
conv_block, fused_opt, embedding_bag) and until this module each carried its
own copy of the shape/dtype eligibility checks and fell back SILENTLY — a
misconfigured flag or an off-by-128 channel count ran the jnp composition
with no trace in the metrics.  This module centralizes:

* **eligibility** — ``decide()`` walks an ordered check list; the first
  failing check becomes the fallback *reason*.
* **telemetry** — every decision increments
  ``pallas_kernel_used_total{kernel}`` or
  ``pallas_kernel_fallback_total{kernel,reason}`` in the PR-3 registry
  (no-ops when FLAGS_telemetry is off), so a silent fallback is now a
  countable event.
* **the probe gate** — the hierarchical-systems cost-model discipline
  (PAPERS.md arXiv 2110.10548): a kernel may be *written* optimistically
  but is *adopted* only where a measured ``tools/op_bench.py --pallas``
  probe shows >= 1.1x over its own fallback on the target device.  Probe
  rows are JSON files archived next to BENCH_*.json (BASELINE.md round-9
  protocol); ``PADDLE_PALLAS_PROBE_DIR`` points at the archive
  (default: the checked-in ``tools/probes/results/``).

Flag-off is INERT: no counters move, so a default-configured run pays one
dict lookup per decision and nothing else.

``PADDLE_PALLAS_INTERPRET=1`` forces interpret-mode execution (kernels run
through the Pallas interpreter on CPU) and waives the backend + probe
checks — the CI ``--kernel-smoke`` leg and the parity tests ride this.
"""

import json
import os
import threading

__all__ = ["decide", "active_kernels", "probe_speedup", "register_probe",
           "reset", "interpret_mode", "KERNELS", "MIN_SPEEDUP"]

# the kernel families sharing this funnel
KERNELS = ("layer_norm", "fused_ln", "conv_block", "fused_opt",
           "embedding_bag", "paged_attention")

# adoption threshold: a probe row below this keeps the fallback
MIN_SPEEDUP = 1.1

_lock = threading.Lock()
_active = set()          # kernels that engaged >= 1 time this process
_probe_overrides = {}    # kernel -> speedup (register_probe: tests/op_bench)
_probe_cache = None      # kernel -> speedup loaded from the archive dir


def interpret_mode():
    """True when PADDLE_PALLAS_INTERPRET forces the Pallas interpreter
    (CPU parity tests / the --kernel-smoke probe leg)."""
    return os.environ.get("PADDLE_PALLAS_INTERPRET", "") in ("1", "true")


def _probe_dir():
    d = os.environ.get("PADDLE_PALLAS_PROBE_DIR", "")
    if d:
        return d
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "tools", "probes", "results")


def _load_probes():
    """kernel -> best measured speedup across every archived probe row.

    A row is any JSON object (one per file, or one per line) with
    ``kernel`` and ``speedup`` keys — exactly what
    ``op_bench.py --pallas --save-probe`` writes.  Unreadable files are
    skipped: a corrupt archive must degrade to "no probe" (fallback),
    never to a crash in the hot path."""
    out = {}
    d = _probe_dir()
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                text = f.read()
        except OSError:
            continue
        rows = []
        try:
            obj = json.loads(text)
            rows = obj if isinstance(obj, list) else [obj]
        except ValueError:
            for line in text.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    pass
        for row in rows:
            if not isinstance(row, dict):
                continue
            k = row.get("kernel")
            try:
                sp = float(row.get("speedup"))
            except (TypeError, ValueError):
                continue
            if k in KERNELS:
                out[k] = max(out.get(k, 0.0), sp)
    return out


def probe_speedup(kernel):
    """Best archived probe speedup for `kernel`, or None if never probed.
    In-memory registrations (register_probe) win over the disk archive."""
    global _probe_cache
    with _lock:
        if kernel in _probe_overrides:
            return _probe_overrides[kernel]
        cache = _probe_cache
    if cache is None:
        # read the archive outside the lock — disk I/O must not stall
        # register_probe()/decide() callers on other threads.  Two racing
        # loaders both read the same files; first publish wins and the
        # loser adopts it, so every caller sees one consistent cache.
        loaded = _load_probes()
        with _lock:
            if _probe_cache is None:
                _probe_cache = loaded
            cache = _probe_cache
    return cache.get(kernel)


def register_probe(kernel, speedup):
    """Record an in-process probe result (op_bench --pallas runs this after
    measuring; tests use it to exercise the gate without touching disk)."""
    with _lock:
        _probe_overrides[kernel] = float(speedup)


def reset():
    """Clear the active set, probe overrides, and the disk cache (tests)."""
    global _probe_cache
    with _lock:
        _active.clear()
        _probe_overrides.clear()
        _probe_cache = None


def _inc(name, **labels):
    from ..core import telemetry

    telemetry.inc(name, **labels)


def decide(kernel, flag=None, checks=(), require_probe=True):
    """Single adoption decision.  Returns (use: bool, reason: str).

    `flag`: the FLAGS_use_pallas_* name gating this family; when the flag
    is off the decision is inert — (False, "flag_off") with NO telemetry,
    so default-configured runs cost one flag read.  `checks` is an ordered
    iterable of (reason, ok) pairs; the first falsy `ok` is the recorded
    fallback reason (eligibility stays next to the kernel that owns it —
    this funnel owns the ordering, counting, and the probe gate).
    `require_probe=False` is for kernels whose adoption predates the probe
    protocol and is pinned by in-step BASELINE numbers instead (fused_ln:
    the round-3 LN lesson is that a microbench win is necessary but not
    sufficient, so an in-step capture outranks the probe row)."""
    from .. import flags as _flags

    if flag is not None and not _flags.flag(flag):
        return False, "flag_off"
    for reason, ok in checks:
        if not ok:
            _inc("pallas_kernel_fallback_total", kernel=kernel,
                 reason=reason)
            return False, reason
    if require_probe and not interpret_mode():
        sp = probe_speedup(kernel)
        if sp is None:
            _inc("pallas_kernel_fallback_total", kernel=kernel,
                 reason="no_probe")
            return False, "no_probe"
        if sp < MIN_SPEEDUP:
            _inc("pallas_kernel_fallback_total", kernel=kernel,
                 reason="probe_below_min")
            return False, "probe_below_min"
    _inc("pallas_kernel_used_total", kernel=kernel)
    with _lock:
        _active.add(kernel)
    return True, "ok"


def active_kernels():
    """Sorted kernels that engaged at least once this process — bench.py
    prints this as `pallas_kernels_active` so a capture records which
    kernels actually ran (a kernel adopted without a probe row is an
    invalid capture, BASELINE.md round-9)."""
    with _lock:
        return sorted(_active)
