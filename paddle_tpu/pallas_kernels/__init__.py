"""Hand-written Pallas TPU kernels — the TPU-native analog of the
reference's fused CUDA ops (paddle/fluid/operators/fused/,
multihead_matmul_op.cu) and its xbyak JIT CPU codegen (operators/jit/)."""

from .flash_attention import flash_attention  # noqa: F401
from . import adoption  # noqa: F401  (probe-gated kernel adoption funnel)
from . import paged_attention  # noqa: F401  (decode-serving gather kernel)
