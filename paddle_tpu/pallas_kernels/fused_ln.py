"""Fused dropout + residual-add + LayerNorm as one Pallas TPU kernel.

TPU-native answer to the reference's fused_fc_elementwise_layernorm_op.cu
(paddle/fluid/operators/fused/ — the reference fuses the fc epilogue, the
elementwise add and the LayerNorm into one CUDA kernel for exactly the
transformer-encoder epilogue this targets), extended with in-kernel
dropout: z = LayerNorm(x + dropout_upscale(y)) in a single HBM pass.

Why a kernel at all: round-4's profile of the flagship BERT step
(bs256/seq128) left ~23 ms of LayerNorm reduce fusions, ~14 ms of
threefry dropout-mask generation and ~20 ms of layout copies that
XLA-level rewrites could not remove (five measured negatives,
BASELINE.md r4).  The round-3 Pallas LayerNorm LOST in-step because
isolating LN broke XLA's LN-neighbor fusions; this kernel fuses those
neighbors (the residual add and the dropout) so there is nothing left to
break, and draws the dropout mask with the on-core PRNG
(pltpu.prng_random_bits) so no threefry program or mask buffer ever
touches HBM — the backward re-draws the identical mask from the saved
32-bit seed pair instead of reading a saved mask.

Numerics: stats and the normalize are f32-internal regardless of the
carry dtype (the repo-wide LN policy); the keep threshold quantizes the
keep probability to round(q * 2^32)/2^32 — the same realized-probability
contract as ops/common.py bernoulli_bytes, at 2^-33 instead of 2^-9
granularity — and the upscale divides by that realized value so
E[out] = x + y exactly.

Off TPU (CPU test mesh) or for un-tileable shapes, an identical-contract
jnp fallback keyed on the same seed pair runs instead; forward and
backward always agree on the mask because both derive it from the saved
seeds with the same (static) path choice.
"""

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # renamed TPUCompilerParams -> CompilerParams across jax releases
    _CompilerParams = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False

from . import prng as _prng

__all__ = ["fused_dropout_add_ln", "fused_ln_fwd", "fused_ln_bwd",
           "ln_stat_shapes"]

_LANES = 128

# shared realized-keep-probability contract (pallas_kernels/prng.py)
_keep_threshold = _prng.keep_threshold
_realized_q = _prng.realized_q


def _pick_rows(n, h, itemsize):
    """Rows per block, bounded by the ~16 MB VMEM scoped-stack limit.

    The backward is the binding constraint (measured: f32 at rows=512,
    h=768 allocates 20.25M — ~52 B per row-element ≈ itemsize*6 + 28 for
    the double-buffered ins/outs plus f32 intermediates).  MUST be a pure
    function of (n, h, itemsize): forward and backward both call it, and
    the dropout mask only replays if both use the same grid blocking.
    """
    # bf16/h=768 -> 512 (measured 24% faster fwd+bwd than 256 at the
    # flagship shape: 0.880 vs 1.165 ms); f32/h=768 -> 256 (512 exceeded
    # the VMEM stack in the pre-r design at 20.25M; the estimate keeps
    # f32 conservative)
    cap = (15 * 1024 * 1024) // (h * (itemsize * 6 + 20))
    for cand in (512, 256, 128, 64, 32, 16, 8):
        if cand <= cap and n % cand == 0:
            return cand
    return None


def ln_stat_shapes(x_shape, begin_norm_axis):
    """(rows, norm_size) split of x_shape at begin_norm_axis.  The leading
    product may be a SYMBOLIC dim (graph-build shape inference traces ops
    with a symbolic batch — core/registry.py _sym_struct); the trailing
    (normalized) product is always concrete."""
    n = 1
    for d in x_shape[:begin_norm_axis]:
        n = n * d
    h = 1
    for d in x_shape[begin_norm_axis:]:
        h *= int(d)
    return n, h


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _draw_keep(seed_ref, rows, h, thr):
    _prng.seed_block_prng(seed_ref)
    return _prng.draw_keep_bits((rows, h), thr)


def _fwd_kernel(seed_ref, x_ref, y_ref, g_ref, b_ref,
                out_ref, r_ref, mean_ref, var_ref, *, thr, eps, rows, h):
    xv = x_ref[...].astype(jnp.float32)
    yv = y_ref[...].astype(jnp.float32)
    if thr is not None:
        keep = _draw_keep(seed_ref, rows, h, thr)
        yv = jnp.where(keep, yv * (1.0 / _realized_q(thr)), 0.0)
    r = xv + yv
    # r is the ONLY tensor the backward reads (plus dz): saving it instead
    # of x and y halves the residual set — the x,y-residual variant
    # measured 96 MB/epilogue live vs the composed emission's ~73, pushing
    # XLA into rematerializing the f32 gelu intermediates (+47 ms/step)
    r_ref[...] = r.astype(r_ref.dtype)
    mean = jnp.mean(r, axis=1, keepdims=True)
    c = r - mean
    var = jnp.mean(c * c, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    z = c * rstd * g_ref[...].astype(jnp.float32) + b_ref[...].astype(
        jnp.float32)
    out_ref[...] = z.astype(out_ref.dtype)
    mean_ref[...] = mean
    var_ref[...] = var


def _bwd_kernel(seed_ref, r_ref, g_ref, mean_ref, var_ref, dz_ref,
                dx_ref, dy_ref, dg_ref, db_ref, *, thr, eps, rows, h):
    r = r_ref[...].astype(jnp.float32)
    if thr is not None:
        keep = _draw_keep(seed_ref, rows, h, thr)
        inv_q = 1.0 / _realized_q(thr)
    rstd = jax.lax.rsqrt(var_ref[...] + eps)
    xhat = (r - mean_ref[...]) * rstd
    dz = dz_ref[...].astype(jnp.float32)
    # per-block dgamma/dbeta partials: blocks must be >=8 sublanes, so the
    # row sum lands in row 0 of an 8-row slab (rows 1-7 zero)
    row0 = jax.lax.broadcasted_iota(jnp.int32, (8, h), 0) == 0
    dg_ref[...] = jnp.where(row0, jnp.sum(dz * xhat, axis=0, keepdims=True),
                            0.0)
    db_ref[...] = jnp.where(row0, jnp.sum(dz, axis=0, keepdims=True), 0.0)
    a = dz * g_ref[...].astype(jnp.float32)
    m1 = jnp.mean(a, axis=1, keepdims=True)
    m2 = jnp.mean(a * xhat, axis=1, keepdims=True)
    dr = rstd * (a - m1 - xhat * m2)
    dx_ref[...] = dr.astype(dx_ref.dtype)
    if thr is not None:
        dr = jnp.where(keep, dr * inv_q, 0.0)
    dy_ref[...] = dr.astype(dy_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------


def _fwd_pallas(x2, y2, gamma, beta, seed, thr, eps, rows):
    n, h = x2.shape
    grid = (n // rows,)
    row_spec = pl.BlockSpec((rows, h), lambda i, *_: (i, 0))
    vec_spec = pl.BlockSpec((1, h), lambda i, *_: (0, 0))
    stat_spec = pl.BlockSpec((rows, 1), lambda i, *_: (i, 0))
    kernel = functools.partial(_fwd_kernel, thr=thr, eps=eps, rows=rows, h=h)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[row_spec, row_spec, vec_spec, vec_spec],
            out_specs=[row_spec, row_spec, stat_spec, stat_spec],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2.dtype),
            jax.ShapeDtypeStruct((n, h), x2.dtype),  # r (backward residual)
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
    )(seed, x2, y2, gamma.reshape(1, h), beta.reshape(1, h))


def _bwd_pallas(r2, gamma, seed, mean, var, dz2, thr, eps, rows):
    n, h = r2.shape
    grid = (n // rows,)
    row_spec = pl.BlockSpec((rows, h), lambda i, *_: (i, 0))
    vec_spec = pl.BlockSpec((1, h), lambda i, *_: (0, 0))
    stat_spec = pl.BlockSpec((rows, 1), lambda i, *_: (i, 0))
    part_spec = pl.BlockSpec((8, h), lambda i, *_: (i, 0))
    kernel = functools.partial(_bwd_kernel, thr=thr, eps=eps, rows=rows, h=h)
    dx, dy, dgp, dbp = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[row_spec, vec_spec, stat_spec, stat_spec, row_spec],
            out_specs=[row_spec, row_spec, part_spec, part_spec],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((n, h), r2.dtype),
            jax.ShapeDtypeStruct((n, h), r2.dtype),
            jax.ShapeDtypeStruct((n // rows * 8, h), jnp.float32),
            jax.ShapeDtypeStruct((n // rows * 8, h), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
    )(seed, r2, gamma.reshape(1, h), mean, var, dz2)
    return dx, dy, jnp.sum(dgp, axis=0), jnp.sum(dbp, axis=0)


# ---------------------------------------------------------------------------
# jnp fallback (CPU test mesh / un-tileable shapes) — same seed contract
# ---------------------------------------------------------------------------


def _fallback_keep(seed, thr, shape):
    key = jax.random.fold_in(
        jax.random.fold_in(jax.random.key(0), seed[0].astype(jnp.uint32)),
        seed[1].astype(jnp.uint32))
    bits = jax.random.bits(key, shape, jnp.uint32)
    return bits < jnp.uint32(thr)


def _fwd_fallback(x2, y2, gamma, beta, seed, thr, eps):
    yv = y2.astype(jnp.float32)
    if thr is not None:
        keep = _fallback_keep(seed, thr, y2.shape)
        yv = jnp.where(keep, yv * (1.0 / _realized_q(thr)), 0.0)
    r = x2.astype(jnp.float32) + yv
    mean = jnp.mean(r, axis=1, keepdims=True)
    c = r - mean
    var = jnp.mean(c * c, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    z = c * rstd * gamma.astype(jnp.float32) + beta.astype(jnp.float32)
    return z.astype(x2.dtype), r.astype(x2.dtype), mean, var


def _bwd_fallback(r2, gamma, seed, mean, var, dz2, thr, eps):
    r = r2.astype(jnp.float32)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (r - mean) * rstd
    dz = dz2.astype(jnp.float32)
    dg = jnp.sum(dz * xhat, axis=0)
    db = jnp.sum(dz, axis=0)
    a = dz * gamma.astype(jnp.float32)
    m1 = jnp.mean(a, axis=1, keepdims=True)
    m2 = jnp.mean(a * xhat, axis=1, keepdims=True)
    dr = rstd * (a - m1 - xhat * m2)
    dx = dr.astype(r2.dtype)
    if thr is not None:
        keep = _fallback_keep(seed, thr, r2.shape)
        dr = jnp.where(keep, dr * (1.0 / _realized_q(thr)), 0.0)
    return dx, dr.astype(r2.dtype), dg, db


# ---------------------------------------------------------------------------
# public custom-vjp entry point
# ---------------------------------------------------------------------------


def _use_pallas(x2, y2):
    # The entry points cast y to x.dtype BEFORE this choice, so the fwd
    # (x2, y2) and bwd (r2, r2 — r stored in x.dtype) calls see the SAME
    # itemsize and pick the SAME rows: a fwd/bwd blocking mismatch would
    # silently desync the re-drawn dropout mask.
    if x2.dtype != y2.dtype:
        raise AssertionError(
            "fused_ln internal: operands must share a dtype by this point")
    from . import adoption

    n, h = x2.shape
    concrete = isinstance(n, int)
    rows = None
    if _HAS_PALLAS and concrete and h % _LANES == 0:
        rows = _pick_rows(n, h, x2.dtype.itemsize)
    # the shared adoption funnel (counts fallbacks; flag-less: this kernel
    # engages by default on TPU).  require_probe=False: adoption predates
    # the probe protocol and is pinned by in-step BASELINE r5 captures —
    # the round-3 LN lesson is that a microbench probe is necessary but
    # not sufficient, so the in-step number outranks it here.
    use, _ = adoption.decide(
        "fused_ln",
        checks=[
            ("no_pallas", _HAS_PALLAS),
            ("backend", jax.default_backend() == "tpu"),
            ("symbolic_shape", concrete),
            ("lanes", h % _LANES == 0),
            ("block_rows", rows is not None),
        ],
        require_probe=False)
    return rows if use else None


def _fwd_any(x2, y2, gamma, beta, seed, thr, eps):
    rows = _use_pallas(x2, y2)
    if rows is not None:
        return _fwd_pallas(x2, y2, gamma, beta, seed, thr, eps, rows)
    return _fwd_fallback(x2, y2, gamma, beta, seed, thr, eps)


def _bwd_any(r2, gamma, seed, mean, var, dz, thr, eps):
    rows = _use_pallas(r2, r2)
    if rows is not None:
        return _bwd_pallas(r2, gamma, seed, mean, var, dz, thr, eps, rows)
    return _bwd_fallback(r2, gamma, seed, mean, var, dz, thr, eps)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _fused(x2, y2, gamma, beta, seed, thr, eps):
    z, _, mean, var = _fwd_any(x2, y2, gamma, beta, seed, thr, eps)
    return z, mean, var


def _fused_fwd(x2, y2, gamma, beta, seed, thr, eps):
    # NB: only r (the post-dropout residual sum) is saved — not x or y.
    # dx == dr and dy == mask*dr/q need neither, and halving the residual
    # set is what keeps XLA from rematting neighbors under memory pressure
    z, r, mean, var = _fwd_any(x2, y2, gamma, beta, seed, thr, eps)
    return (z, mean, var), (r, gamma, seed, mean, var)


def _fused_bwd(thr, eps, res, cts):
    # stats are auxiliary (stop-gradded by the wrapper): only dz flows
    dz, _, _ = cts
    r, gamma, seed, mean, var = res
    dx, dy, dg, db = _bwd_any(r, gamma, seed, mean, var, dz, thr, eps)
    return dx, dy, dg.astype(gamma.dtype), db.astype(gamma.dtype), None


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_ln_fwd(x, y, gamma, beta, dropout_prob, seed, epsilon,
                 begin_norm_axis):
    """Op-mode forward (explicit-grad-op integration, cf. the dropout op's
    Mask contract): returns (z, r, mean [N], variance [N]) with NO vjp
    tracking — the program-level grad op calls fused_ln_bwd with the saved
    r/seed/stats instead.  r is the post-dropout residual sum, the only
    large backward residual."""
    n, h = ln_stat_shapes(x.shape, begin_norm_axis)
    thr = _keep_threshold(dropout_prob)
    seed = jnp.asarray(seed).reshape(2).astype(jnp.uint32)
    # the epilogue computes in x's carry dtype: casting y up front keeps
    # the fwd/bwd block choice a function of ONE dtype (mask replay)
    z, r, mean, var = _fwd_any(x.reshape(n, h),
                               y.astype(x.dtype).reshape(n, h),
                               gamma.reshape(h), beta.reshape(h), seed, thr,
                               float(epsilon))
    return (z.reshape(x.shape), r.reshape(x.shape), mean.reshape(n),
            var.reshape(n))


def fused_ln_bwd(r, gamma, seed, mean, var, dz, dropout_prob, epsilon,
                 begin_norm_axis):
    """Op-mode backward: (dx, dy, dgamma, dbeta) from the saved residual
    sum r; the dropout mask for dy is re-drawn from the SAME seed and
    grid blocking as the forward."""
    n, h = ln_stat_shapes(r.shape, begin_norm_axis)
    thr = _keep_threshold(dropout_prob)
    seed = jnp.asarray(seed).reshape(2).astype(jnp.uint32)
    dx, dy, dg, db = _bwd_any(
        r.reshape(n, h), gamma.reshape(h), seed,
        mean.reshape(n, 1).astype(jnp.float32),
        var.reshape(n, 1).astype(jnp.float32), dz.reshape(n, h), thr,
        float(epsilon))
    return (dx.reshape(r.shape), dy.reshape(r.shape),
            dg.astype(gamma.dtype), db.astype(gamma.dtype))


def fused_dropout_add_ln(x, y, gamma, beta, dropout_prob, seed, epsilon=1e-5,
                         begin_norm_axis=None, return_stats=False):
    """z = LayerNorm(x + dropout_upscale(y)) in one fused pass.

    x, y: same shape, normalized over the trailing dims starting at
    ``begin_norm_axis`` (default: the last dim).  gamma/beta: [H] scale
    and shift.  seed: [2] uint32/int32 array — the dropout mask is a pure
    function of it (the backward re-draws the identical mask; pass the
    same seed to reproduce a step).  dropout_prob <= 0 disables dropout
    (exact LN(x+y)); the training upscale divides by the REALIZED keep
    probability round(q*2^32)/2^32.

    Returns z, or (z, mean, variance) with f32 stats of shape
    [prod(leading)] when return_stats=True.
    """
    if begin_norm_axis is None:
        begin_norm_axis = x.ndim - 1
    n, h = ln_stat_shapes(x.shape, begin_norm_axis)
    thr = _keep_threshold(dropout_prob)
    x2 = x.reshape(n, h)
    # compute in x's carry dtype (see fused_ln_fwd: keeps the fwd/bwd
    # block choice single-dtype so the dropout mask replays)
    y2 = y.astype(x.dtype).reshape(n, h)
    seed = jnp.asarray(seed).reshape(2).astype(jnp.uint32)
    gamma = gamma.reshape(h)
    beta = beta.reshape(h)
    z, mean, var = _fused(x2, y2, gamma, beta, seed, thr, float(epsilon))
    if return_stats:
        return (z.reshape(x.shape),
                jax.lax.stop_gradient(mean).reshape(n),
                jax.lax.stop_gradient(var).reshape(n))
    return z.reshape(x.shape)
