"""Fused optimizer-step Pallas kernel (Adam / momentum) over the flat
fused-group buffer, with the bf16 param-carry cast folded in.

The PR-2 `fuse_optimizer` pass already coalesces per-parameter updates
into one ``fused_adam``/``fused_momentum`` op, so the XLA update is a
single elementwise pass — but under the bf16 param carry
(FLAGS_layout_match_params) the step still streams the parameter set
through HBM three times: moment recurrence + AXPY reads, the f32 master
write, and the separate f32->bf16 carry cast.  This kernel does all of it
in ONE pass per block: each 8x128 tile of the flat group is read once,
the new moments / master / bf16 carry copy are written from VMEM.

**Bitwise contract** (the whole point — enforced by
tests/test_pallas_blocks.py over 3 steps): every elementwise expression
mirrors the unfused ``fused_adam`` lowering verbatim, in the same dtype
and the same operation order (f32 elementwise add/mul/sqrt/div are IEEE
deterministic, so identical expressions are identical bits regardless of
blocking).  Per-member bias correction is preserved: each member's scalar
``lr_t = lr * sqrt(1-b2pow)/(1-b1pow)`` is computed OUTSIDE the kernel
with the exact unfused expression, members are padded to whole 1024-
element blocks so no block straddles two members, and the kernel reads
its block's lr_t from a per-block scalar array.  The bf16 copy is
``p_new.astype(bfloat16)`` — bitwise-identical to the carry cast
build_block_fn would otherwise emit, so correctness never depends on the
kernel engaging; only HBM traffic does.

Adoption is probe-gated like every family (adoption.py):
FLAGS_use_pallas_fused_opt + eligibility + a >=1.1x tools/probes row.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False

from . import adoption

__all__ = ["fused_adam_step", "fused_momentum_step", "fused_opt_checks"]

# one grid step = one (8, 128) f32 tile of the flat group
_BLOCK = 8 * 128


def fused_opt_checks(params, grads, moments=()):
    """Ordered (reason, ok) pairs for adoption.decide()."""
    f32 = jnp.dtype(jnp.float32)
    return [
        ("no_pallas", _HAS_PALLAS),
        ("backend", adoption.interpret_mode()
         or jax.default_backend() == "tpu"),
        ("empty_group", len(params) > 0),
        ("dtype", all(p.dtype == f32 for p in params)
         and all(m.dtype == f32 for ms in moments for m in ms)),
    ]


def _interp():
    return adoption.interpret_mode() or jax.default_backend() != "tpu"


def _pad_flat(tensors):
    """Concat of member flats, each zero-padded to whole blocks.  Returns
    (flat_2d [rows, 128], sizes, block_counts, offsets-in-padded-space)."""
    sizes = [int(np.prod(t.shape)) for t in tensors]
    counts = [max((n + _BLOCK - 1) // _BLOCK, 1) for n in sizes]
    segs, offs, off = [], [], 0
    for t, n, c in zip(tensors, sizes, counts):
        flat = t.reshape(-1)
        pad = c * _BLOCK - n
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), t.dtype)])
        segs.append(flat)
        offs.append(off)
        off += c * _BLOCK
    return (jnp.concatenate(segs).reshape(-1, 128), sizes, counts, offs)


def _unpad(flat2d, sizes, counts, offs, shapes, dtype=None):
    flat = flat2d.reshape(-1)
    outs = []
    for n, off, shp in zip(sizes, offs, shapes):
        seg = flat[off:off + n].reshape(shp)
        outs.append(seg if dtype is None else seg.astype(dtype))
    return outs


def _adam_kernel(p_ref, g_ref, m1_ref, m2_ref, lrt_ref,
                 p_out, m1_out, m2_out, bf_out, *, beta1, beta2, epsilon):
    # expression mirrors ops/optimizer_ops.py fused_adam verbatim (bitwise)
    b1 = jnp.float32(beta1)
    b2 = jnp.float32(beta2)
    g = g_ref[...]
    m1n = b1 * m1_ref[...] + (1.0 - b1) * g
    m2n = b2 * m2_ref[...] + (1.0 - b2) * g * g
    u = m1n / (jnp.sqrt(m2n) + epsilon)
    p = p_ref[...] - lrt_ref[0, 0] * u
    p_out[...] = p
    m1_out[...] = m1n
    m2_out[...] = m2n
    bf_out[...] = p.astype(jnp.bfloat16)


def _momentum_kernel(p_ref, g_ref, v_ref, lr_ref, p_out, v_out, bf_out, *,
                     mu, use_nesterov):
    # mirrors ops/optimizer_ops.py fused_momentum verbatim (bitwise)
    g = g_ref[...]
    lr = lr_ref[0, 0]
    v = jnp.float32(mu) * v_ref[...] + g
    if use_nesterov:
        p = p_ref[...] - (g + jnp.float32(mu) * v) * lr
    else:
        p = p_ref[...] - lr * v
    p_out[...] = p
    v_out[...] = v
    bf_out[...] = p.astype(jnp.bfloat16)


def _tile_specs(n_blocks):
    tile = pl.BlockSpec((8, 128), lambda i: (i, 0))
    scalar = pl.BlockSpec((1, 1), lambda i: (i, 0))
    return tile, scalar


def fused_adam_step(params, grads, m1s, m2s, lr, b1pows, b2pows,
                    beta1=0.9, beta2=0.999, epsilon=1e-8):
    """One fused Adam step over the group.  Returns
    (p_news, m1ns, m2ns, b1pow_outs, b2pow_outs, bf16_news) — the last is
    the bf16 carry copies (``p_new.astype(bfloat16)`` per member), emitted
    from the same VMEM tile so the carry never costs an extra HBM pass.

    All scalar algebra (lr_t, beta-pow advance) uses the EXACT unfused
    expressions so the result is bitwise-equal to fused_adam's jnp path."""
    dt = params[0].dtype
    lr_ = lr.reshape(()).astype(dt)
    b1 = jnp.asarray(beta1, dt)
    b2 = jnp.asarray(beta2, dt)
    shapes = [p.shape for p in params]

    p_flat, sizes, counts, offs = _pad_flat(params)
    g_flat, _, _, _ = _pad_flat([g.astype(dt) for g in grads])
    m1_flat, _, _, _ = _pad_flat(m1s)
    m2_flat, _, _, _ = _pad_flat(m2s)

    # per-member scalar lr_t (unfused expression), replicated per block
    lrts = []
    for b1pow, b2pow in zip(b1pows, b2pows):
        b1p = b1pow.reshape(()).astype(dt)
        b2p = b2pow.reshape(()).astype(dt)
        lrts.append(lr_ * jnp.sqrt(1.0 - b2p) / (1.0 - b1p))
    n_blocks = sum(counts)
    lrt_blocks = jnp.repeat(jnp.stack(lrts), np.asarray(counts),
                            total_repeat_length=n_blocks).reshape(-1, 1)

    tile, scalar = _tile_specs(n_blocks)
    rows = n_blocks * 8
    p_new, m1n, m2n, bf = pl.pallas_call(
        functools.partial(_adam_kernel, beta1=beta1, beta2=beta2,
                          epsilon=epsilon),
        grid=(n_blocks,),
        in_specs=[tile, tile, tile, tile, scalar],
        out_specs=[tile, tile, tile, tile],
        out_shape=[jax.ShapeDtypeStruct((rows, 128), dt),
                   jax.ShapeDtypeStruct((rows, 128), dt),
                   jax.ShapeDtypeStruct((rows, 128), dt),
                   jax.ShapeDtypeStruct((rows, 128), jnp.bfloat16)],
        interpret=_interp(),
    )(p_flat, g_flat, m1_flat, m2_flat, lrt_blocks)

    return (_unpad(p_new, sizes, counts, offs, shapes),
            _unpad(m1n, sizes, counts, offs, shapes),
            _unpad(m2n, sizes, counts, offs, shapes),
            [(b.reshape(()) * b1).reshape(b.shape) for b in b1pows],
            [(b.reshape(()) * b2).reshape(b.shape) for b in b2pows],
            _unpad(bf, sizes, counts, offs, shapes))


def fused_momentum_step(params, grads, vels, lr, mu=0.0, use_nesterov=False):
    """One fused momentum step.  Returns (p_news, v_news, bf16_news).
    L2 regularization is pre-applied by the caller on the gradients (the
    unfused lowering folds it into g_flat before the recurrence)."""
    dt = params[0].dtype
    lr_ = lr.reshape(()).astype(dt)
    shapes = [p.shape for p in params]

    p_flat, sizes, counts, offs = _pad_flat(params)
    g_flat, _, _, _ = _pad_flat([g.astype(dt) for g in grads])
    v_flat, _, _, _ = _pad_flat(vels)

    n_blocks = sum(counts)
    lr_blocks = jnp.broadcast_to(lr_.reshape(1, 1), (n_blocks, 1))
    tile, scalar = _tile_specs(n_blocks)
    rows = n_blocks * 8
    p_new, v_new, bf = pl.pallas_call(
        functools.partial(_momentum_kernel, mu=mu,
                          use_nesterov=bool(use_nesterov)),
        grid=(n_blocks,),
        in_specs=[tile, tile, tile, scalar],
        out_specs=[tile, tile, tile],
        out_shape=[jax.ShapeDtypeStruct((rows, 128), dt),
                   jax.ShapeDtypeStruct((rows, 128), dt),
                   jax.ShapeDtypeStruct((rows, 128), jnp.bfloat16)],
        interpret=_interp(),
    )(p_flat, g_flat, v_flat, lr_blocks)

    return (_unpad(p_new, sizes, counts, offs, shapes),
            _unpad(v_new, sizes, counts, offs, shapes),
            _unpad(bf, sizes, counts, offs, shapes))


def stash_bf16_carry(ctx, bf16_news):
    """Hand the kernel's bf16 copies to the step function: for every
    carried param in this group (its f32 master lives under
    ``<name>@MASTER``), drop the kernel's cast under
    ``<name>@PALLAS_BF16`` — build_block_fn prefers the stash over
    re-casting the f32 ParamOut (bitwise the same value, one less
    elementwise pass over the parameter bytes)."""
    if ctx is None or ctx.op is None or getattr(ctx, "env", None) is None:
        return
    names = ctx.op.input("Param")
    for n, bf in zip(names, bf16_news):
        if (n + "@MASTER") in ctx.env:
            ctx.env[n + "@PALLAS_BF16"] = bf
