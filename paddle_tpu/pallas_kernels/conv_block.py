"""Fused conv + batch-norm + relu trunk block (Pallas TPU kernel).

TPU-native answer to the reference's conv_bn fusion passes
(paddle/fluid/framework/ir/conv_bn_fuse_pass.cc and the fused
conv2d_fusion CUDA op): the XLA composition materializes the conv output
to HBM, re-reads it for the BN statistics pass, and re-reads it AGAIN for
the normalize+relu pass — at ResNet-50 trunk shapes the BN elementwise
passes are pure HBM-bandwidth cost (~20% of a step, BASELINE.md round-3
profile).  This kernel keeps one image's conv output VMEM-resident and
applies the folded BN affine (+ relu) before it ever leaves the core.

Two variants, per the reference's is_test split:

* **inference** — the BN affine folds to per-channel (a, b) from the
  RUNNING statistics outside the kernel; one pass computes
  ``relu(conv(x, w) * a + b)``.
* **training** — pass 1 computes the conv and accumulates per-image
  per-channel sum / sum-of-squares partials (the batch statistics the op
  contract must emit); the cross-image reduction and the affine fold are
  scalar work outside; pass 2 is a small elementwise affine+relu kernel
  over the VMEM-blocked conv output.

The conv itself is the standard shifted-matmul decomposition: for a
``kh x kw`` filter, kh*kw MXU matmuls ``[OH*OW, C_in] @ [C_in, C_out]``
over strided slices of the padded input — channels ride the lane
dimension, accumulation is f32.

Gradients: the public training entry is a ``custom_vjp`` whose backward
is the jnp fallback composition's VJP (conv transpose rules + the BN
affine chain) — the kernel carries no hand-written backward, so the grads
agree with the reference composition by construction (interp-mode parity
test: tests/test_pallas_blocks.py).

Adoption is probe-gated (adoption.py): FLAGS_use_pallas_conv_block off,
shape/dtype ineligibility, or a missing/sub-1.1x tools/probes row all
fall back to the jnp composition with a counted reason.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False

from . import adoption

__all__ = ["conv_bn_relu_inference", "conv_bn_relu_train",
           "conv_bn_relu_reference", "conv_block_checks"]

# VMEM plan cap for one grid step (input plane + output plane + filter +
# f32 accumulator), conservative against the ~16 MB budget
_VMEM_CAP = 12 * 1024 * 1024


def _out_size(h, k, s, p):
    return (h + 2 * p - k) // s + 1


def conv_block_checks(x_shape, w_shape, strides, paddings, dilations=(1, 1),
                      groups=1, data_format="NCHW", itemsize=4):
    """Ordered (reason, ok) eligibility pairs for adoption.decide().

    The reasons are the telemetry labels — keep them short and stable."""
    sh = tuple(strides)
    pd = tuple(paddings)
    static = all(isinstance(d, int) for d in tuple(x_shape) + tuple(w_shape))
    checks = [
        ("no_pallas", _HAS_PALLAS),
        ("backend", adoption.interpret_mode()
         or jax.default_backend() == "tpu"),
        ("layout", data_format in ("NCHW", "AnyLayout")),
        ("symbolic_shape", static),
        ("rank", len(x_shape) == 4 and len(w_shape) == 4),
        ("groups", int(groups) == 1),
        ("dilation", tuple(dilations) in ((1, 1), ())),
        ("stride", len(sh) == 2 and sh[0] == sh[1] and sh[0] in (1, 2)),
        ("padding", len(pd) == 2 and pd[0] == pd[1]),
    ]
    if not (static and len(x_shape) == 4 and len(w_shape) == 4
            and len(sh) == 2 and len(pd) == 2):
        return checks
    n, c, h, w_ = x_shape
    co, ci, kh, kw = w_shape
    checks += [
        ("kernel_size", kh == kw and kh in (1, 3, 5, 7)),
        ("channels", c % 8 == 0 or c in (3, 4)),  # conv1 takes RGB
        ("out_channels", co % 8 == 0),
    ]
    oh = _out_size(h, kh, sh[0], pd[0])
    ow = _out_size(w_, kw, sh[0], pd[0])
    checks.append(("out_size", oh > 0 and ow > 0))
    plan = (c * (h + 2 * pd[0]) * (w_ + 2 * pd[0]) * 4
            + co * ci * kh * kw * 4 + 2 * co * oh * ow * 4)
    checks.append(("vmem", plan <= _VMEM_CAP))
    return checks


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _conv_image(x, w, stride, pad, oh, ow):
    """[OH*OW, C_out] f32 conv of one image: kh*kw shifted MXU matmuls.

    x: [C, H, W] f32, w: [C_out, C_in, kh, kw] f32.  The kh*kw python
    loop unrolls at trace time; each strided slice is a free VMEM view."""
    c = x.shape[0]
    co, _, kh, kw = w.shape
    xp = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad)))
    acc = jnp.zeros((oh * ow, co), jnp.float32)
    for i in range(kh):
        for j in range(kw):
            patch = lax.slice(
                xp, (0, i, j),
                (c, i + (oh - 1) * stride + 1, j + (ow - 1) * stride + 1),
                (1, stride, stride))
            rows = patch.reshape(c, oh * ow).T
            acc = acc + lax.dot_general(
                rows, w[:, :, i, j].T,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    return acc


def _infer_kernel(x_ref, w_ref, a_ref, b_ref, y_ref, *, stride, pad, relu):
    oh, ow = y_ref.shape[2], y_ref.shape[3]
    acc = _conv_image(x_ref[0].astype(jnp.float32),
                      w_ref[...].astype(jnp.float32), stride, pad, oh, ow)
    y = acc * a_ref[...] + b_ref[...]
    if relu:
        y = jnp.maximum(y, 0.0)
    y_ref[0] = y.T.reshape(y_ref.shape[1], oh, ow).astype(y_ref.dtype)


def _train_conv_kernel(x_ref, w_ref, conv_ref, s_ref, ss_ref, *, stride,
                       pad):
    oh, ow = conv_ref.shape[2], conv_ref.shape[3]
    acc = _conv_image(x_ref[0].astype(jnp.float32),
                      w_ref[...].astype(jnp.float32), stride, pad, oh, ow)
    conv_ref[0] = acc.T.reshape(conv_ref.shape[1], oh, ow)
    # per-image per-channel partials: the batch moments reduce over these
    # [N, C_out] strips outside the kernel (one tiny jnp sum)
    s_ref[...] = jnp.sum(acc, axis=0, keepdims=True)
    ss_ref[...] = jnp.sum(acc * acc, axis=0, keepdims=True)


def _affine_relu_kernel(c_ref, a_ref, b_ref, y_ref, *, relu):
    cv = c_ref[0]
    co = cv.shape[0]
    y = cv * a_ref[...].reshape(co, 1, 1) + b_ref[...].reshape(co, 1, 1)
    if relu:
        y = jnp.maximum(y, 0.0)
    y_ref[0] = y.astype(y_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing (grid over the batch; one image per step)
# ---------------------------------------------------------------------------


def _interp():
    return adoption.interpret_mode() or jax.default_backend() != "tpu"


def _infer_pallas(x, w, a, b, stride, pad, relu):
    n, c, h, w_ = x.shape
    co, _, kh, kw = w.shape
    oh, ow = _out_size(h, kh, stride, pad), _out_size(w_, kw, stride, pad)
    return pl.pallas_call(
        functools.partial(_infer_kernel, stride=stride, pad=pad, relu=relu),
        grid=(n,),
        in_specs=[pl.BlockSpec((1, c, h, w_), lambda i: (i, 0, 0, 0)),
                  pl.BlockSpec((co, c, kh, kw), lambda i: (0, 0, 0, 0)),
                  pl.BlockSpec((1, co), lambda i: (0, 0)),
                  pl.BlockSpec((1, co), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((1, co, oh, ow), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, co, oh, ow), x.dtype),
        interpret=_interp(),
    )(x, w, a.reshape(1, co).astype(jnp.float32),
      b.reshape(1, co).astype(jnp.float32))


def _train_pallas(x, w, stride, pad):
    n, c, h, w_ = x.shape
    co, _, kh, kw = w.shape
    oh, ow = _out_size(h, kh, stride, pad), _out_size(w_, kw, stride, pad)
    conv, s, ss = pl.pallas_call(
        functools.partial(_train_conv_kernel, stride=stride, pad=pad),
        grid=(n,),
        in_specs=[pl.BlockSpec((1, c, h, w_), lambda i: (i, 0, 0, 0)),
                  pl.BlockSpec((co, c, kh, kw), lambda i: (0, 0, 0, 0))],
        out_specs=[pl.BlockSpec((1, co, oh, ow), lambda i: (i, 0, 0, 0)),
                   pl.BlockSpec((1, co), lambda i: (i, 0)),
                   pl.BlockSpec((1, co), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((n, co, oh, ow), jnp.float32),
                   jax.ShapeDtypeStruct((n, co), jnp.float32),
                   jax.ShapeDtypeStruct((n, co), jnp.float32)],
        interpret=_interp(),
    )(x, w)
    return conv, s, ss


def _affine_pallas(conv, a, b, relu, out_dtype):
    n, co, oh, ow = conv.shape
    return pl.pallas_call(
        functools.partial(_affine_relu_kernel, relu=relu),
        grid=(n,),
        in_specs=[pl.BlockSpec((1, co, oh, ow), lambda i: (i, 0, 0, 0)),
                  pl.BlockSpec((1, co), lambda i: (0, 0)),
                  pl.BlockSpec((1, co), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((1, co, oh, ow), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, co, oh, ow), out_dtype),
        interpret=_interp(),
    )(conv, a.reshape(1, co).astype(jnp.float32),
      b.reshape(1, co).astype(jnp.float32))


# ---------------------------------------------------------------------------
# jnp reference composition (the fallback AND the backward)
# ---------------------------------------------------------------------------


def _ref_conv(x, w, stride, pad):
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCHW", "OIHW", "NCHW"))
    return lax.conv_general_dilated(
        x.astype(jnp.float32), w.astype(jnp.float32),
        window_strides=(stride, stride), padding=[(pad, pad), (pad, pad)],
        dimension_numbers=dn)


def _fold_affine(scale, bias, mean, var, eps):
    inv = 1.0 / jnp.sqrt(var.astype(jnp.float32) + eps)
    a = inv * scale.astype(jnp.float32)
    return a, bias.astype(jnp.float32) - mean.astype(jnp.float32) * a


def _ref_train(x, w, scale, bias, eps, stride, pad, relu):
    conv = _ref_conv(x, w, stride, pad)
    m = jnp.mean(conv, axis=(0, 2, 3))
    v = jnp.mean(jnp.square(conv), axis=(0, 2, 3)) - jnp.square(m)
    a, b = _fold_affine(scale, bias, m, v, eps)
    y = conv * a.reshape(1, -1, 1, 1) + b.reshape(1, -1, 1, 1)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype), m, v


def _ref_infer(x, w, scale, bias, mean, var, eps, stride, pad, relu):
    a, b = _fold_affine(scale, bias, mean, var, eps)
    y = _ref_conv(x, w, stride, pad) * a.reshape(1, -1, 1, 1) \
        + b.reshape(1, -1, 1, 1)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


def conv_bn_relu_reference(x, w, scale, bias, mean, var, eps=1e-5, stride=1,
                           pad=0, relu=True, is_test=False):
    """The jnp fallback.  Training returns (y, batch_mean, batch_var);
    inference returns (y, mean, var) — running stats passed through."""
    if is_test:
        return (_ref_infer(x, w, scale, bias, mean, var, eps, stride, pad,
                           relu), mean.astype(jnp.float32),
                var.astype(jnp.float32))
    return _ref_train(x, w, scale, bias, eps, stride, pad, relu)


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9))
def conv_bn_relu_inference(x, w, scale, bias, mean, var, eps=1e-5, stride=1,
                           pad=0, relu=True):
    """Folded-scale inference block: relu(conv(x, w) * a + b) in one
    kernel pass, a/b folded from the RUNNING statistics.  Backward (rare —
    is_test graphs — but the op contract stays differentiable) is the
    reference composition's VJP."""
    a, b = _fold_affine(scale, bias, mean, var, eps)
    return _infer_pallas(x, w, a, b, stride, pad, relu)


def _infer_fwd(x, w, scale, bias, mean, var, eps, stride, pad, relu):
    a, b = _fold_affine(scale, bias, mean, var, eps)
    return (_infer_pallas(x, w, a, b, stride, pad, relu),
            (x, w, scale, bias, mean, var))


def _infer_bwd(eps, stride, pad, relu, res, ct):
    x, w, scale, bias, mean, var = res
    _, vjp_fn = jax.vjp(
        lambda *args: _ref_infer(*args, eps, stride, pad, relu),
        x, w, scale, bias, mean, var)
    return vjp_fn(ct)


conv_bn_relu_inference.defvjp(_infer_fwd, _infer_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def conv_bn_relu_train(x, w, scale, bias, eps, stride, pad, relu):
    """Training block: (y, batch_mean [C_out] f32, batch_var [C_out] f32).

    Forward runs the two-pass kernel (conv+stat partials, then
    affine+relu); backward is the reference composition's VJP."""
    return _train_fwd_impl(x, w, scale, bias, eps, stride, pad, relu)


def _train_fwd_impl(x, w, scale, bias, eps, stride, pad, relu):
    conv, s, ss = _train_pallas(x, w, stride, pad)
    n, co, oh, ow = conv.shape
    cnt = float(n * oh * ow)
    m = jnp.sum(s, axis=0) / cnt
    v = jnp.sum(ss, axis=0) / cnt - jnp.square(m)
    a, b = _fold_affine(scale, bias, m, v, eps)
    y = _affine_pallas(conv, a, b, relu, x.dtype)
    return y, m, v


def _train_fwd(x, w, scale, bias, eps, stride, pad, relu):
    outs = _train_fwd_impl(x, w, scale, bias, eps, stride, pad, relu)
    return outs, (x, w, scale, bias)


def _train_bwd(eps, stride, pad, relu, res, cts):
    x, w, scale, bias = res
    _, vjp_fn = jax.vjp(
        lambda x_, w_, s_, b_: _ref_train(x_, w_, s_, b_, eps, stride, pad,
                                          relu),
        x, w, scale, bias)
    cts = tuple(
        c if c is not None else jnp.zeros(o.shape, o.dtype)
        for c, o in zip(cts, _abstract_train_outs(x, w, scale, stride, pad)))
    return vjp_fn(cts)


def _abstract_train_outs(x, w, scale, stride, pad):
    co = w.shape[0]
    oh = _out_size(x.shape[2], w.shape[2], stride, pad)
    ow = _out_size(x.shape[3], w.shape[3], stride, pad)
    return (jax.ShapeDtypeStruct((x.shape[0], co, oh, ow), x.dtype),
            jax.ShapeDtypeStruct((co,), jnp.float32),
            jax.ShapeDtypeStruct((co,), jnp.float32))


conv_bn_relu_train.defvjp(_train_fwd, _train_bwd)
