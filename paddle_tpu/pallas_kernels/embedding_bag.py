"""Block-sparse embedding-bag gather/sum Pallas kernel.

The recommender path (`distributed/sparse_table.py`) feeds a dense row
buffer ``rows [U, D]`` (the unique rows this step touches, already pulled
from the host-resident sparse table) plus per-sample bags of local ids
``ids [B, K]`` (-1 pads ragged bags).  The generic lowering is
``jnp.take`` into a [B, K, D] intermediate followed by a masked sum —
B*K*D of HBM writes + reads that exist only to be reduced.  This kernel
uses scalar-prefetched ids to steer the input DMA directly: grid step
(b, k) fetches ONE (1, D) row chosen by ``ids[b, k]`` and accumulates it
into the (1, D) output bag in VMEM, so the [B, K, D] intermediate never
materializes.  Invalid (-1) ids are clamped to row 0 for the DMA and
masked to zero in the accumulate.

The backward (row gradients = scatter-add of the bag cotangent over
valid ids) routes through ``jax.vjp`` of the jnp fallback — the ISSUE's
"grads via the fallback VJP" contract; ids are integer inputs and get a
float0 cotangent.

Adoption: FLAGS_use_pallas_embedding_bag + ``bag_checks`` eligibility +
a >= 1.1x tools/probes row, all through adoption.decide().
"""

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # renamed TPUCompilerParams -> CompilerParams across jax releases
    _CompilerParams = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False

from . import adoption

__all__ = ["embedding_bag", "embedding_bag_reference", "bag_checks"]


def bag_checks(rows_shape, ids_shape, dtype):
    """Ordered (reason, ok) pairs for adoption.decide()."""
    static = all(isinstance(d, int) and d >= 0
                 for d in tuple(rows_shape) + tuple(ids_shape))
    return [
        ("no_pallas", _HAS_PALLAS),
        ("backend", adoption.interpret_mode()
         or jax.default_backend() == "tpu"),
        ("symbolic_shape", static),
        ("rank", len(rows_shape) == 2 and len(ids_shape) == 2),
        ("dtype", jnp.dtype(dtype) == jnp.dtype(jnp.float32)),
        ("row_width", static and len(rows_shape) == 2
         and rows_shape[1] % 128 == 0),
        ("empty", static and all(d > 0 for d in tuple(rows_shape)
                                 + tuple(ids_shape))),
    ]


def _interp():
    return adoption.interpret_mode() or jax.default_backend() != "tpu"


def embedding_bag_reference(rows, ids):
    """jnp fallback: masked take + sum.  ids < 0 are padding."""
    idx = jnp.maximum(ids, 0)
    g = jnp.take(rows, idx, axis=0)              # [B, K, D]
    mask = (ids >= 0)[..., None]
    return jnp.sum(jnp.where(mask, g, 0.0), axis=1).astype(rows.dtype)


def _bag_kernel(ids_ref, row_ref, out_ref):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _zero():
        out_ref[...] = jnp.zeros_like(out_ref)

    b = pl.program_id(0)
    valid = ids_ref[b, k] >= 0
    row = row_ref[...].astype(jnp.float32)
    out_ref[...] += jnp.where(valid, row, 0.0).astype(out_ref.dtype)


def _bag_pallas(rows, ids):
    u, d = rows.shape
    bb, k = ids.shape
    # the prefetched ids steer the row DMA; -1 pads clamp to row 0 (masked
    # to zero inside the kernel before the accumulate)
    row_spec = pl.BlockSpec(
        (1, d), lambda b, j, ids_ref: (jnp.maximum(ids_ref[b, j], 0), 0))
    out_spec = pl.BlockSpec((1, d), lambda b, j, ids_ref: (b, 0))
    call = functools.partial(
        pl.pallas_call,
        _bag_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bb, k),
            in_specs=[row_spec],
            out_specs=out_spec,
        ),
        out_shape=jax.ShapeDtypeStruct((bb, d), rows.dtype),
        interpret=_interp(),
    )
    if not _interp():
        # k must iterate sequentially (the out block accumulates across it)
        call = functools.partial(
            call, compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "arbitrary")))
    return call()(ids.astype(jnp.int32), rows)


@jax.custom_vjp
def embedding_bag(rows, ids):
    """Pallas embedding-bag: out[b] = sum_k rows[ids[b, k]] over ids >= 0.
    Backward differentiates the jnp fallback (scatter-add into rows)."""
    return _bag_pallas(rows, ids)


def _bag_fwd(rows, ids):
    return _bag_pallas(rows, ids), (rows, ids)


def _bag_bwd(res, dout):
    rows, ids = res
    _, vjp = jax.vjp(embedding_bag_reference, rows, ids)
    drows, _ = vjp(dout.astype(rows.dtype))
    import numpy as np

    return drows, np.zeros(ids.shape, dtype=jax.dtypes.float0)


embedding_bag.defvjp(_bag_fwd, _bag_bwd)
