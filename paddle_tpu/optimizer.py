"""Optimizers (parity: python/paddle/fluid/optimizer.py, 21 classes).

`minimize` = append_backward + regularization/clip + per-param update ops,
exactly the reference's structure; the update ops themselves are fused XLA
computations (ops/optimizer_ops.py) that run inside the same compiled step
as forward/backward — no separate optimizer kernel launches.
"""

import numpy as np

from .backward import append_backward
from .framework import Parameter, Variable, default_main_program, default_startup_program
from .initializer import Constant
from .layer_helper import LayerHelper
from .utils import unique_name

__all__ = [
    "SGD",
    "Momentum",
    "Adagrad",
    "Adam",
    "Adamax",
    "DecayedAdagrad",
    "Adadelta",
    "RMSProp",
    "Ftrl",
    "Lamb",
    "SGDOptimizer",
    "MomentumOptimizer",
    "DGCMomentumOptimizer",
    "AdagradOptimizer",
    "AdamOptimizer",
    "AdamaxOptimizer",
    "DecayedAdagradOptimizer",
    "AdadeltaOptimizer",
    "RMSPropOptimizer",
    "FtrlOptimizer",
    "LambOptimizer",
    "LarsMomentum",
    "LarsMomentumOptimizer",
    "GradientMergeOptimizer",
    "ExponentialMovingAverage",
    "ModelAverage",
    "RecomputeOptimizer",
    "LookaheadOptimizer",
    "PipelineOptimizer",
]


# ZeRO-1 weight-update sharding support table (transpiler/collective.py
# ShardedGradAllReduce): optimizer op types whose update is elementwise
# over the param — a dim-0 shard of (Param, Grad, state) computes exactly
# the shard of the full update, so each replica can own 1/nranks of the
# rows.  Values are the param-shaped state slots (input names; the
# matching *Out output slots alias the same vars).  Scalar state
# (LearningRate, Beta*Pow) stays replicated.  lamb / lars_momentum /
# dpsgd are deliberately absent: their updates take global norms (or
# fresh noise) over the whole param, which a shard cannot reproduce.
ZERO1_SHARDABLE_SLOTS = {
    "sgd": (),
    "momentum": (("Velocity", "VelocityOut"),),
    "adam": (("Moment1", "Moment1Out"), ("Moment2", "Moment2Out")),
    "adagrad": (("Moment", "MomentOut"),),
    "adamax": (("Moment", "MomentOut"), ("InfNorm", "InfNormOut")),
    "decayed_adagrad": (("Moment", "MomentOut"),),
    "adadelta": (("AvgSquaredGrad", "AvgSquaredGradOut"),
                 ("AvgSquaredUpdate", "AvgSquaredUpdateOut")),
    "rmsprop": (("Moment", "MomentOut"), ("MeanSquare", "MeanSquareOut"),
                ("MeanGrad", "MeanGradOut")),
    "ftrl": (("SquaredAccumulator", "SquaredAccumOut"),
             ("LinearAccumulator", "LinearAccumOut")),
}


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None,
                 grad_clip=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self._learning_rate_map = {}
        self._accumulators = {}  # accum_name -> {param_name: var}
        self.helper = None
        self.type = getattr(self, "type", "optimizer")

    # -- learning rate -------------------------------------------------------
    def _create_global_learning_rate(self):
        from .framework import in_dygraph_mode

        program = default_main_program()
        lr = self._learning_rate_map.get(program)
        if in_dygraph_mode() and not isinstance(self._learning_rate,
                                                (int, float, Variable)):
            # dygraph scheduler (dygraph/learning_rate_scheduler.py): advance
            # one step per update and refresh the eager lr value
            import jax.numpy as jnp

            val = float(self._learning_rate.step())
            if lr is None:
                lr = program.global_block().create_var(
                    name=unique_name.generate("learning_rate"), shape=(1,),
                    dtype="float32", persistable=True)
                lr.stop_gradient = True
                self._learning_rate_map[program] = lr
            lr._ivar = jnp.asarray([val], jnp.float32)
            return
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[program] = self._learning_rate
            return
        lr_name = unique_name.generate("learning_rate")
        lr_var = program.global_block().create_var(
            name=lr_name, shape=(1,), dtype="float32", persistable=True
        )
        lr_var.stop_gradient = True
        Constant(float(self._learning_rate))(lr_var)
        self._learning_rate_map[program] = lr_var

    def _global_learning_rate(self, program=None):
        program = program or default_main_program()
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        base = self._global_learning_rate()
        param_lr = param.optimize_attr.get("learning_rate", 1.0)
        if param_lr == 1.0:
            return base
        from . import layers

        with default_main_program()._lr_schedule_guard():
            return layers.scale(base, scale=float(param_lr))

    @property
    def current_step_lr(self):
        from .core.executor import global_scope

        lr = self._global_learning_rate()
        if lr is None:
            return self._learning_rate
        if lr._ivar is not None:  # dygraph: eager value (scheduler or const)
            return float(np.asarray(lr._ivar).ravel()[0])
        t = global_scope().find_var(lr.name)
        return float(np.asarray(t.get_tensor().numpy())[0]) if t else None

    # -- accumulators --------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                        shape=None):
        accum = self._accumulators.setdefault(name, {})
        if param.name in accum:
            return accum[param.name]
        var = default_main_program().global_block().create_var(
            name=unique_name.generate("%s_%s" % (param.name, name)),
            shape=shape if shape is not None else param.shape,
            dtype=dtype or param.dtype,
            persistable=True,
        )
        var.stop_gradient = True
        Constant(float(fill_value))(var)
        accum[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- main ---------------------------------------------------------------
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_optimize(loss, startup_program, params_grads)
        return optimize_ops, params_grads

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        from .framework import in_dygraph_mode, _dygraph_tracer

        if in_dygraph_mode():
            # dygraph: grads were produced by loss.backward() (tape engine);
            # wrap each accumulated grad array in a fresh eager Variable so
            # clip/regularizer/update ops can consume it by slot.
            params = parameter_list or _dygraph_tracer().all_parameters()
            block = default_main_program().global_block()
            params_grads = []
            for p in params:
                if not p.trainable:
                    continue
                if p._grad_ivar is None:
                    params_grads.append((p, None))
                    continue
                g = block.create_var(
                    name=p.name + "@GRAD", dtype=p.dtype,
                    shape=tuple(p._grad_ivar.shape), stop_gradient=True)
                g._ivar = p._grad_ivar
                params_grads.append((p, g))
            return params_grads
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_optimize(self, loss, startup_program, params_grads):
        from .framework import in_dygraph_mode

        if in_dygraph_mode():
            # parameter updates must not be taped (they are not part of any
            # future backward); matches the reference running optimizer ops
            # outside the autograd trace
            from .dygraph.base import no_grad_guard

            with no_grad_guard():
                return self.apply_gradients(params_grads)
        return self.apply_gradients(params_grads)

    def apply_gradients(self, params_grads):
        from .clip import append_gradient_clip_ops
        from .regularizer import append_regularization_ops

        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        else:
            params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        return self._create_optimization_pass(params_grads)

    def _create_optimization_pass(self, params_grads):
        program = default_main_program()
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_global_learning_rate()
        self._create_accumulators(
            program.global_block(), [p for p, g in params_grads if g is not None]
        )
        optimize_ops = []
        # current_block (not global_block): under a conditional (e.g.
        # GradientMergeOptimizer's k-step boundary Switch) the update ops
        # must land INSIDE the branch; outside any control flow the
        # current block IS the global block
        target_block = program.current_block()
        for param_and_grad in params_grads:
            if param_and_grad[1] is None:
                continue
            if not param_and_grad[0].trainable:
                continue
            with program._optimized_guard(param_and_grad):
                op = self._append_optimize_op(target_block,
                                              param_and_grad)
                optimize_ops.append(op)
        self._finish_update(target_block, params_grads)
        return optimize_ops

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block, params_grads):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError


class SGDOptimizer(Optimizer):
    type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            type="sgd",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param]},
        )


class MomentumOptimizer(Optimizer):
    type = "momentum"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            type="momentum",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "Velocity": [velocity],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentumOptimizer(Optimizer):
    type = "lars_momentum"

    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            type="lars_momentum",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "Velocity": [velocity],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay},
        )


class AdagradOptimizer(Optimizer):
    type = "adagrad"

    def __init__(self, learning_rate, epsilon=1e-6,
                 initial_accumulator_value=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        return block.append_op(
            type="adagrad",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "Moment": [moment],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lazy_mode = lazy_mode

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            type="adam",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "Moment1": [m1],
                "Moment2": [m2],
                "LearningRate": [self._create_param_lr(param_and_grad)],
                "Beta1Pow": [b1p],
                "Beta2Pow": [b2p],
            },
            outputs={
                "ParamOut": [param],
                "Moment1Out": [m1],
                "Moment2Out": [m2],
                "Beta1PowOut": [b1p],
                "Beta2PowOut": [b2p],
            },
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "lazy_mode": self._lazy_mode},
        )


class AdamaxOptimizer(Optimizer):
    type = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            type="adamax",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "Moment": [self._get_accumulator("moment", param)],
                "InfNorm": [self._get_accumulator("inf_norm", param)],
                "LearningRate": [self._create_param_lr(param_and_grad)],
                "Beta1Pow": [self._get_accumulator("beta1_pow_acc", param)],
            },
            outputs={
                "ParamOut": [param],
                "MomentOut": [self._get_accumulator("moment", param)],
                "InfNormOut": [self._get_accumulator("inf_norm", param)],
            },
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
        )

    def _finish_update(self, block, params_grads):
        for p, g in params_grads:
            if g is None:
                continue
            b1p = self._get_accumulator("beta1_pow_acc", p)
            with default_main_program()._optimized_guard([p, g]):
                block.append_op(
                    type="scale",
                    inputs={"X": [b1p]},
                    outputs={"Out": [b1p]},
                    attrs={"scale": self._beta1},
                )


class DecayedAdagradOptimizer(Optimizer):
    type = "decayed_adagrad"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        return block.append_op(
            type="decayed_adagrad",
            inputs={
                "Param": [param], "Grad": [grad], "Moment": [moment],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    type = "adadelta"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("__avg_squared_grad", p)
            self._add_accumulator("__avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        g2 = self._get_accumulator("__avg_squared_grad", param)
        u2 = self._get_accumulator("__avg_squared_update", param)
        return block.append_op(
            type="adadelta",
            inputs={"Param": [param], "Grad": [grad],
                    "AvgSquaredGrad": [g2], "AvgSquaredUpdate": [u2]},
            outputs={"ParamOut": [param], "AvgSquaredGradOut": [g2],
                     "AvgSquaredUpdateOut": [u2]},
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    type = "rmsprop"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        mom = self._get_accumulator("momentum", param)
        ms = self._get_accumulator("mean_square", param)
        mg = self._get_accumulator("mean_grad", param)
        return block.append_op(
            type="rmsprop",
            inputs={
                "Param": [param], "Grad": [grad], "Moment": [mom],
                "MeanSquare": [ms], "MeanGrad": [mg],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param], "MomentOut": [mom],
                     "MeanSquareOut": [ms], "MeanGradOut": [mg]},
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered},
        )


class FtrlOptimizer(Optimizer):
    type = "ftrl"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        sq = self._get_accumulator("squared", param)
        lin = self._get_accumulator("linear", param)
        return block.append_op(
            type="ftrl",
            inputs={
                "Param": [param], "Grad": [grad],
                "SquaredAccumulator": [sq], "LinearAccumulator": [lin],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class LambOptimizer(AdamOptimizer):
    type = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 exclude_from_weight_decay_fn=None, **kwargs):
        super().__init__(learning_rate, beta1, beta2, epsilon, **kwargs)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(param):
            wd = 0.0
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            type="lamb",
            inputs={
                "Param": [param], "Grad": [grad],
                "Moment1": [m1], "Moment2": [m2],
                "LearningRate": [self._create_param_lr(param_and_grad)],
                "Beta1Pow": [b1p], "Beta2Pow": [b2p],
            },
            outputs={"ParamOut": [param], "Moment1Out": [m1],
                     "Moment2Out": [m2], "Beta1PowOut": [b1p],
                     "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "weight_decay": wd},
        )


class ExponentialMovingAverage:
    """EMA of parameters (reference optimizer.py:2869)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or ""
        self._ema_vars = {}
        from . import layers

        block = default_main_program().global_block()
        self._params = [p for p in block.all_parameters() if p.trainable]
        for p in self._params:
            ema = block.create_var(
                name=unique_name.generate(p.name + ".ema"),
                shape=p.shape, dtype=p.dtype, persistable=True,
            )
            Constant(0.0)(ema)
            self._ema_vars[p.name] = ema

    def update(self):
        from . import layers

        block = default_main_program().global_block()
        for p in self._params:
            ema = self._ema_vars[p.name]
            block.append_op(
                type="scale", inputs={"X": [ema]}, outputs={"Out": [ema]},
                attrs={"scale": self._decay},
            )
            tmp = layers.scale(p, scale=1.0 - self._decay)
            block.append_op(
                type="elementwise_add",
                inputs={"X": [ema], "Y": [tmp]},
                outputs={"Out": [ema]},
            )

    def apply(self, executor, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def guard():
            from .core.executor import global_scope

            scope = global_scope()
            backup = {}
            for p in self._params:
                backup[p.name] = np.asarray(scope.find_var(p.name).get_tensor().numpy())
                ema_v = scope.find_var(self._ema_vars[p.name].name)
                if ema_v is not None:
                    scope.var(p.name).set(ema_v.get_tensor().get())
            try:
                yield
            finally:
                if need_restore:
                    for name, val in backup.items():
                        scope.var(name).set(val)

        return guard()

    def restore(self, executor):
        pass


class ModelAverage(Optimizer):
    """Parameter averaging over a window (reference optimizer.py:2567).
    Simplified: maintains running sum + count; apply() swaps averages in."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kwargs):
        super().__init__(0.0, **kwargs)
        self._sums = {}
        self._counts = {}
        block = default_main_program().global_block()
        params = [p for p in block.all_parameters() if p.trainable]
        for p in params:
            s = block.create_var(
                name=unique_name.generate(p.name + ".avg_sum"),
                shape=p.shape, dtype=p.dtype, persistable=True)
            Constant(0.0)(s)
            self._sums[p.name] = s
            block.append_op(
                type="elementwise_add", inputs={"X": [s], "Y": [p]},
                outputs={"Out": [s]})
        self._params = params
        cnt = block.create_var(name=unique_name.generate("avg_count"),
                               shape=(1,), dtype="float32", persistable=True)
        Constant(0.0)(cnt)
        block.append_op(type="increment", inputs={"X": [cnt]},
                        outputs={"Out": [cnt]}, attrs={"step": 1.0})
        self._count = cnt

    def apply(self, executor, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def guard():
            from .core.executor import global_scope

            scope = global_scope()
            backup = {}
            cnt = float(np.asarray(scope.find_var(self._count.name).get_tensor().numpy())[0])
            cnt = max(cnt, 1.0)
            for p in self._params:
                backup[p.name] = np.asarray(scope.find_var(p.name).get_tensor().numpy())
                s = np.asarray(scope.find_var(self._sums[p.name].name).get_tensor().numpy())
                scope.var(p.name).set(s / cnt)
            try:
                yield
            finally:
                if need_restore:
                    for name, val in backup.items():
                        scope.var(name).set(val)

        return guard()


class RecomputeOptimizer(Optimizer):
    """Activation recompute (reference optimizer.py:3396).  The vjp-based
    grads already replay forward locally; segment checkpoints map to
    jax.checkpoint policies applied at block-compile time."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        loss.block.program._recompute_checkpoints = [
            c.name if isinstance(c, Variable) else c for c in (self._checkpoints or [])
        ]
        return self._optimizer.minimize(loss, startup_program, parameter_list,
                                        no_grad_set)


class GradientMergeOptimizer:
    """Batch-merge gradient accumulation (reference
    ir/multi_batch_merge_pass.cc + the dist_mnist_batch_merge.py payload;
    later-fluid exposes the same thing as GradientMergeOptimizer).

    Every step accumulates grads into persistable ``@GRAD@MERGED``
    buffers; every ``k_steps``-th step a conditional_block applies the
    inner optimizer to the merged (optionally averaged) grads and zeroes
    the buffers — k microbatches behave like one k-times-larger batch.
    The conditional lowers to lax.cond (traced predicate), so the whole
    thing stays inside the one compiled step."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        if k_steps < 1:
            raise ValueError("k_steps must be >= 1")
        self.inner_optimizer = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        from . import layers
        from .layers.control_flow import Switch

        if self.k_steps == 1:
            return self.inner_optimizer.minimize(
                loss, startup_program, parameter_list, no_grad_set)
        params_grads = self.inner_optimizer.backward(
            loss, startup_program, parameter_list, no_grad_set)
        main = loss.block.program
        block = main.global_block()

        # persistable step counter (int64 [1], zero-initialized)
        counter = layers.create_global_var(
            shape=[1], value=0, dtype="int64", persistable=True,
            name=unique_name.generate("gradient_merge_step"))
        layers.increment(counter, value=1, in_place=True)

        merged_pgs = []
        for p, g in params_grads:
            if g is None:
                continue
            # NB: no "@GRAD" in the name — the lowering treats @GRAD-
            # suffixed vars as optional gradient temporaries, but this
            # buffer is persistable cross-step state
            m = block.create_var(
                name=unique_name.generate(p.name + ".merged_grad"),
                shape=p.shape, dtype=p.dtype, persistable=True)
            m.stop_gradient = True
            Constant(0.0)(m)
            # m += g
            block.append_op(type="elementwise_add",
                            inputs={"X": [m.name], "Y": [g.name]},
                            outputs={"Out": [m.name]}, attrs={})
            merged_pgs.append((p, m))

        k_var = layers.fill_constant(shape=[1], dtype="int64",
                                     value=self.k_steps)
        zero = layers.fill_constant(shape=[1], dtype="int64", value=0)
        rem = layers.elementwise_mod(counter, k_var)
        is_boundary = layers.equal(rem, zero)

        ops = None
        sw = Switch()
        with sw.case(is_boundary):
            apply_pgs = []
            for p, m in merged_pgs:
                if self.avg:
                    eff = layers.scale(m, scale=1.0 / self.k_steps)
                else:
                    eff = m
                apply_pgs.append((p, eff))
            ops = self.inner_optimizer.apply_gradients(apply_pgs)
            for _p, m in merged_pgs:
                layers.assign(layers.scale(m, scale=0.0), m)
        with sw.default():
            pass
        return ops, params_grads


class LookaheadOptimizer:
    """Lookahead wrapper (reference optimizer.py:3689): slow/fast weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k

    def minimize(self, loss, startup_program=None):
        ops, pgs = self.inner_optimizer.minimize(loss, startup_program)
        block = default_main_program().global_block()
        for p, g in pgs:
            if g is None:
                continue
            slow = block.create_var(
                name=unique_name.generate(p.name + ".slow"),
                shape=p.shape, dtype=p.dtype, persistable=True)
            # slow init: copy of param at startup
            sb = default_startup_program().global_block()
            if not sb.has_var(slow.name):
                sb.create_var(name=slow.name, shape=p.shape, dtype=p.dtype,
                              persistable=True)
            if not sb.has_var(p.name):
                sb.create_var(name=p.name, shape=p.shape, dtype=p.dtype,
                              persistable=True)
            sb.append_op(type="assign", inputs={"X": [p.name]},
                         outputs={"Out": [slow.name]})
            # every step: slow += alpha*(fast-slow)/k approximation of the
            # k-step sync (static-graph-friendly smoothing)
            from . import layers

            diff = layers.elementwise_sub(p, slow)
            upd = layers.scale(diff, scale=self.alpha / self.k)
            block.append_op(type="elementwise_add",
                            inputs={"X": [slow], "Y": [upd]},
                            outputs={"Out": [slow]})
        return ops, pgs


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer


class PipelineOptimizer:
    """Pipeline-parallel training (reference optimizer.py:3103
    PipelineOptimizer + framework/pipeline_trainer.cc SectionWorker).

    Splits the trained program at `cut_list` boundaries into sections, each
    assigned a place from `place_list` (e.g. CPUPlace for the embedding/IO
    stage, TPUPlace for the dense stage — the reference's CTR pipeline
    shape).  Execution is the host-queue scheduler in trainer.py: one
    worker thread per section, microbatches flowing through native blocking
    queues, parameters updated per-microbatch in the shared scope (the
    reference's async SectionWorker semantics).  Entered via
    ``exe.train_from_dataset`` exactly like the reference
    (PipelineTrainer).

    TPU note: within one process a single chip serializes device sections;
    this class's win is overlapping host (parse/embedding/CPU math) stages
    with the compiled XLA stage.  Multi-chip GPipe-style stage sharding
    over a mesh axis is `paddle_tpu.parallel.make_pipeline_step`
    (parallel/pipeline.py): stage-sharded params, ppermute activation
    handoffs, jax.grad through the skewed microbatch schedule — the
    reference's distinct-device section placement
    (pipeline_trainer.cc:24), done the SPMD way.
    """

    def __init__(self, optimizer, cut_list=None, place_list=None,
                 concurrency_list=None, queue_size=30, sync_steps=1,
                 start_cpu_core_id=0):
        self._optimizer = optimizer
        self._cut_list = cut_list or []
        self._place_list = place_list or []
        self._concurrency_list = concurrency_list
        self._queue_size = queue_size
        self._sync_steps = sync_steps

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        program = loss.block.program
        opt_ops, params_grads = self._optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        self._split_program(program)
        return opt_ops, params_grads

    # -- splitting -----------------------------------------------------------
    def _split_program(self, program):
        block = program.global_block()
        ops = list(block.ops)

        # section boundaries: a section closes once every cut var of its
        # boundary has been produced
        bounds = []
        cut_idx = 0
        pending = (set(v.name for v in self._cut_list[cut_idx])
                   if cut_idx < len(self._cut_list) else None)
        start = 0
        for i, op in enumerate(ops):
            if pending is None:
                continue
            pending -= set(op.output_arg_names)
            if not pending:
                bounds.append((start, i + 1))
                start = i + 1
                cut_idx += 1
                pending = (set(v.name for v in self._cut_list[cut_idx])
                           if cut_idx < len(self._cut_list) else None)
        if pending:
            raise ValueError(
                "PipelineOptimizer: cut vars %s are never produced by any "
                "op — check cut_list (data vars and typos cannot be cut "
                "points)" % sorted(pending))
        bounds.append((start, len(ops)))

        def is_persistable(name):
            v = block._find_var_recursive(name)
            return v is not None and v.persistable

        def is_data(name):
            v = block._find_var_recursive(name)
            return v is not None and v.is_data

        produced = [
            set(n for op in ops[s:e] for n in op.output_arg_names if n)
            for s, e in bounds
        ]
        reads = [
            set(n for op in ops[s:e] for n in op.input_arg_names
                if n and not is_persistable(n))
            for s, e in bounds
        ]
        K = len(bounds)

        def carry_into(i):
            """Names section i must receive from upstream."""
            out = set()
            for j in range(i, K):
                for n in reads[j]:
                    made_before = any(n in produced[t] for t in range(i))
                    made_between = any(n in produced[t] for t in range(i, j))
                    # dataset feeds enter at section 0 and are relayed down
                    if not made_between and (made_before or is_data(n)):
                        out.add(n)
            return out

        sections = []
        for i, (s, e) in enumerate(bounds):
            sec_prog = program.clone()
            sb = sec_prog.global_block()
            sb.ops = sb.ops[s:e]
            # sections share param buffers across concurrent executors: XLA
            # buffer donation in one section would delete arrays another
            # section still reads (core/executor.py honors this flag)
            sec_prog._no_donate = True
            sec_prog._bump_version()
            in_names = sorted(carry_into(i))
            out_names = sorted(carry_into(i + 1)) if i + 1 < K else []
            place = (self._place_list[i] if i < len(self._place_list)
                     else None)
            sections.append({
                "program": sec_prog,
                "place": place,
                "in_names": in_names,
                "out_names": out_names,
            })
        program._pipeline_opt = {
            "sections": sections,
            "queue_size": self._queue_size,
            "sync_steps": self._sync_steps,
        }
        return sections


class DGCMomentumOptimizer(MomentumOptimizer):
    """Momentum + Deep Gradient Compression (reference optimizer.py:870;
    dgc_op + sparse_all_reduce_op_handle).

    Per step each grad goes through the dgc op (momentum correction, local
    accumulation, top-(1-sparsity) selection with error feedback); the
    momentum update then consumes the sparsified gradient.  On TPU the
    compressed gradient is a dense-with-zeros tensor — summing it across
    replicas (GradAllReduce) reproduces the reference's sparse allgather
    semantics over ICI.  `rampup_begin_step` is honored statically: it
    configures the ratio schedule at build time (the reference switches
    per-step; our compiled program applies the final ratio from step 0,
    documented deviation)."""

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 num_trainers=None, **kwargs):
        super().__init__(learning_rate, momentum,
                         use_nesterov=use_nesterov, **kwargs)
        self._rampup_begin_step = rampup_begin_step
        self._sparsity = list(sparsity)
        self._ratio = max(1.0 - float(self._sparsity[-1]), 1e-6)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("dgc_u", p)
            self._add_accumulator("dgc_v", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        u = self._get_accumulator("dgc_u", param)
        v = self._get_accumulator("dgc_v", param)
        helper = LayerHelper("dgc")
        encode = helper.create_variable_for_type_inference(grad.dtype)
        grad_out = helper.create_variable_for_type_inference(grad.dtype)
        block.append_op(
            type="dgc",
            inputs={"U": [u], "V": [v], "Grad": [grad]},
            outputs={"UOut": [u], "VOut": [v], "EncodeGrad": [encode],
                     "GradOut": [grad_out]},
            attrs={"m": self._momentum, "ratio": self._ratio,
                   "use_nesterov": self._use_nesterov,
                   "rampup_begin_step": float(self._rampup_begin_step)},
        )
        # momentum (incl. nesterov) is folded into the DGC accumulators;
        # the compressed gradient applies with plain SGD
        return block.append_op(
            type="sgd",
            inputs={
                "Param": [param],
                "Grad": [grad_out],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param]},
            attrs={},
        )
