"""Host side of the C API (parity: paddle/fluid/framework/c/c_api.cc +
inference/capi/).

The C library (native/csrc_capi/paddle_tpu_c.cc) embeds CPython and calls
these functions; each returns only C-friendly scalars/bytes so the C layer
stays a thin marshalling shim.  Handles are integers into module-level
registries (the C side owns their lifetime via *_destroy)."""

import threading

import numpy as np

_registry = {}
_next_handle = [1]
_lock = threading.Lock()


def _new_handle(obj):
    with _lock:
        h = _next_handle[0]
        _next_handle[0] += 1
        _registry[h] = obj
    return h


def _get(h):
    return _registry[h]


def destroy(h):
    with _lock:
        _registry.pop(h, None)
    return 0


# -- op registry query (framework/c/c_api.cc analog) --------------------------


def num_ops():
    import paddle_tpu  # noqa: F401  (populates the registry)
    from paddle_tpu.core.registry import all_op_types

    return len(all_op_types())


def op_names():
    import paddle_tpu  # noqa: F401
    from paddle_tpu.core.registry import all_op_types

    return "\n".join(sorted(all_op_types()))


# -- trainer ------------------------------------------------------------------


class _Trainer:
    def __init__(self, model_dir, place):
        import paddle_tpu as fluid

        self.fluid = fluid
        main, startup, feeds, fetches = fluid.io.load_train_model(model_dir)
        self.main, self.startup = main, startup
        self.feed_names, self.fetch_names = feeds, fetches
        p = fluid.TPUPlace(0) if place == "tpu" else fluid.CPUPlace()
        self.exe = fluid.Executor(p)
        self.scope = fluid.Scope()
        with fluid.scope_guard(self.scope):
            self.exe.run(startup)
        self.pending_feed = {}

    def feed(self, name, arr):
        self.pending_feed[name] = arr

    def step(self):
        with self.fluid.scope_guard(self.scope):
            fetch = [self.main.global_block().var(n)
                     for n in self.fetch_names]
            outs = self.exe.run(self.main, feed=dict(self.pending_feed),
                                fetch_list=fetch)
        self.pending_feed.clear()
        return [np.asarray(o) for o in outs]


def trainer_create(model_dir, place):
    return _new_handle(_Trainer(model_dir, place))


# -- predictor ----------------------------------------------------------------


class _Predictor:
    def __init__(self, model_dir, place):
        import paddle_tpu as fluid

        self.fluid = fluid
        p = fluid.TPUPlace(0) if place == "tpu" else fluid.CPUPlace()
        self.exe = fluid.Executor(p)
        self.scope = fluid.Scope()
        with fluid.scope_guard(self.scope):
            prog, feeds, fetches = fluid.io.load_inference_model(
                model_dir, self.exe)
        self.prog, self.feed_names = prog, feeds
        self.fetch_vars = fetches
        self.pending_feed = {}
        self.outputs = []

    def feed(self, name, arr):
        self.pending_feed[name] = arr

    def run(self):
        with self.fluid.scope_guard(self.scope):
            outs = self.exe.run(self.prog, feed=dict(self.pending_feed),
                                fetch_list=self.fetch_vars)
        self.pending_feed.clear()
        self.outputs = [np.ascontiguousarray(np.asarray(o)) for o in outs]
        return len(self.outputs)


def predictor_create(model_dir, place):
    return _new_handle(_Predictor(model_dir, place))


# -- shared marshalling (both handle kinds) -----------------------------------

_DTYPES = {"float32": np.float32, "float64": np.float64,
           "int32": np.int32, "int64": np.int64}


def feed_buffer(handle, name, data_bytes, dtype, dims):
    arr = np.frombuffer(data_bytes, dtype=_DTYPES[dtype]).reshape(
        [int(d) for d in dims]).copy()
    _get(handle).feed(name, arr)
    return 0


def trainer_step(handle):
    """Run one step; returns the first fetch as a float (loss)."""
    outs = _get(handle).step()
    return float(np.asarray(outs[0]).reshape(-1)[0])


def predictor_run(handle):
    return _get(handle).run()


def output_ndim(handle, i):
    return len(_get(handle).outputs[i].shape)


def output_dim(handle, i, d):
    return int(_get(handle).outputs[i].shape[d])


def output_bytes(handle, i):
    return _get(handle).outputs[i].astype(np.float32).tobytes()
