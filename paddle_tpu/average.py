"""WeightedAverage accumulator (reference python/paddle/fluid/average.py)."""

import numpy as np

__all__ = ["WeightedAverage"]


def _is_number_or_matrix_(var):
    return isinstance(var, (int, float, complex, np.ndarray)) or (
        hasattr(var, "__array__"))


class WeightedAverage:
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not _is_number_or_matrix_(value):
            raise ValueError("add() expects a number or numpy array")
        if not isinstance(weight, (int, float)):
            raise ValueError("weight must be a number")
        if self.numerator is None:
            self.numerator = np.asarray(value, "float64") * weight
            self.denominator = float(weight)
        else:
            self.numerator = self.numerator + np.asarray(value,
                                                         "float64") * weight
            self.denominator += float(weight)

    def eval(self):
        if self.numerator is None or self.denominator == 0:
            raise ValueError("eval() before add(), or zero total weight")
        return self.numerator / self.denominator
