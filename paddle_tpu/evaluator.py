"""Deprecated Evaluator classes (reference
python/paddle/fluid/evaluator.py: ChunkEvaluator:127, EditDistance:218,
DetectionMAP:299).  The reference itself deprecates these in favor of
fluid.metrics; kept for API parity as thin delegates that build the same
metric ops and accumulate across batches via fluid.metrics."""

import numpy as np

from . import layers, metrics

__all__ = ["ChunkEvaluator", "EditDistance", "DetectionMAP"]


class Evaluator:
    """Base (evaluator.py:40): create states in the startup program and
    update them per batch; reset() zeroes the python-side accumulator."""

    def __init__(self, name=None):
        self._name = name
        self._metric = None

    def reset(self, executor=None, reset_program=None):
        if self._metric is not None:
            self._metric.reset()

    def eval(self, executor=None, eval_program=None):
        return self._metric.eval()


class ChunkEvaluator(Evaluator):
    """Precision/recall/F1 over chunked sequence labels
    (evaluator.py:127): wraps layers.chunk_eval + metrics.ChunkEvaluator
    accumulation."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super().__init__()
        (precision, recall, f1, num_infer_chunks, num_label_chunks,
         num_correct_chunks) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        self._metric = metrics.ChunkEvaluator()
        self.metrics = [precision, recall, f1]
        self.fetches = [num_infer_chunks, num_label_chunks,
                        num_correct_chunks]

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self._metric.update(int(np.asarray(num_infer_chunks).sum()),
                            int(np.asarray(num_label_chunks).sum()),
                            int(np.asarray(num_correct_chunks).sum()))


class EditDistance(Evaluator):
    """Average edit distance accumulation (evaluator.py:218)."""

    def __init__(self, input, label, ignored_tokens=None):
        super().__init__()
        distances, seq_num = layers.edit_distance(
            input=input, label=label, ignored_tokens=ignored_tokens)
        self._metric = metrics.EditDistance()
        self.metrics = [distances]
        self.fetches = [distances, seq_num]

    def update(self, distances, seq_num):
        d = np.asarray(distances, "float64")
        self._metric.update(d, int(np.asarray(seq_num).sum()))


class DetectionMAP(Evaluator):
    """mAP over detection batches (evaluator.py:299): builds the
    detection_map op per batch and averages its MAP output."""

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0,
                 overlap_threshold=0.5, evaluate_difficult=True,
                 ap_version="integral"):
        super().__init__()
        if gt_difficult is not None:
            label = layers.concat([gt_label, gt_difficult, gt_box], axis=1)
        else:
            label = layers.concat([gt_label, gt_box], axis=1)
        # build the detection_map op directly (no layer wrapper exists;
        # ops/coverage_tail.py detection_map)
        from .layer_helper import LayerHelper

        helper = LayerHelper("detection_map_eval")
        outs = {nm: helper.create_variable_for_type_inference("float32")
                for nm in ("AccumPosCount", "AccumTruePos",
                           "AccumFalsePos", "MAP")}
        helper.append_op(
            type="detection_map",
            inputs={"DetectRes": [input], "Label": [label]},
            outputs={k: [v] for k, v in outs.items()},
            attrs={"overlap_threshold": overlap_threshold,
                   "evaluate_difficult": evaluate_difficult,
                   "class_num": class_num or 1,
                   "background_label": background_label,
                   "ap_type": ap_version})
        helper_map = outs["MAP"]
        self._maps = []
        self.metrics = [helper_map]
        self.fetches = [helper_map]

    def reset(self, executor=None, reset_program=None):
        self._maps = []

    def update(self, batch_map):
        self._maps.append(float(np.asarray(batch_map).reshape(-1)[0]))

    def eval(self, executor=None, eval_program=None):
        if not self._maps:
            raise ValueError("eval() before update()")
        return float(np.mean(self._maps))
