"""MNIST models (BASELINE config 1; mirrors reference
tests/book/test_recognize_digits.py model builders)."""

import paddle_tpu as fluid


def build_mlp(img_shape=(784,), num_classes=10):
    img = fluid.layers.data("img", shape=list(img_shape))
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(img, 200, act="relu")
    h = fluid.layers.fc(h, 200, act="relu")
    logits = fluid.layers.fc(h, num_classes)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
    return img, label, logits, loss, acc


def build_conv(num_classes=10):
    """LeNet-style convnet (reference: conv_net in test_recognize_digits)."""
    img = fluid.layers.data("img", shape=[1, 28, 28])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    c1 = fluid.layers.conv2d(img, 20, 5, act="relu")
    p1 = fluid.layers.pool2d(c1, pool_size=2, pool_stride=2)
    c2 = fluid.layers.conv2d(p1, 50, 5, act="relu")
    p2 = fluid.layers.pool2d(c2, pool_size=2, pool_stride=2)
    logits = fluid.layers.fc(p2, num_classes)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
    return img, label, logits, loss, acc
