"""BERT-style transformer encoder (BASELINE config 3).

Built from the fluid layer API exactly as the reference's ERNIE/BERT scripts
compose it (fc/matmul/softmax/dropout/layer_norm; the fused attention path in
the reference is inference-only multihead_matmul_op.cu — here attention is
left to XLA fusion, with a Pallas flash-attention kernel as the fast path,
see paddle_tpu/pallas_kernels/flash_attention.py).

TP sharding: pass ``mesh_tp=True`` to annotate qkv/ffn weights with
PartitionSpec axis names consumed by the executor for tensor parallelism.
"""

import paddle_tpu as fluid
from paddle_tpu.param_attr import ParamAttr


class BertConfig:
    def __init__(self, vocab_size=30522, hidden=768, layers=12, heads=12,
                 ffn=3072, max_pos=512, type_vocab=2, dropout=0.1):
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.layers = layers
        self.heads = heads
        self.ffn = ffn
        self.max_pos = max_pos
        self.type_vocab = type_vocab
        self.dropout = dropout


BERT_BASE = BertConfig()
BERT_TINY = BertConfig(vocab_size=1024, hidden=64, layers=2, heads=4,
                       ffn=128, max_pos=64)


def _attr(name, tp_axes=None, use_tp=False):
    return ParamAttr(name=name, sharding=tp_axes if use_tp else None)


def multi_head_attention(x, cfg, prefix, is_test=False, use_tp=False,
                         attn_mask=None):
    """Self-attention from primitives; XLA fuses QK^T-softmax-V; the Pallas
    fast path replaces the inner three ops when enabled."""
    h, heads = cfg.hidden, cfg.heads
    d = h // heads
    # three separate projections: a fused [h, 3h] QKV emission (the
    # reference's multihead_matmul_op.cu input layout) was measured SLOWER
    # on-chip — 843.5 vs 896.0 seqs/s at bs256/seq128 bf16-carry — the
    # q/k/v slices force an extra materialization pass that outweighs the
    # larger MXU tile (BASELINE.md round-4 table)
    q = fluid.layers.fc(x, h, num_flatten_dims=2,
                        param_attr=_attr(prefix + "_q_w", (None, "model"), use_tp))
    k = fluid.layers.fc(x, h, num_flatten_dims=2,
                        param_attr=_attr(prefix + "_k_w", (None, "model"), use_tp))
    v = fluid.layers.fc(x, h, num_flatten_dims=2,
                        param_attr=_attr(prefix + "_v_w", (None, "model"), use_tp))

    def split_heads(t):
        t = fluid.layers.reshape(t, [0, 0, heads, d])
        return fluid.layers.transpose(t, [0, 2, 1, 3])

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    import os as _os

    if is_test or not cfg.dropout:
        # fast path: one fused Pallas flash-attention kernel (no
        # attention-prob dropout in this mode, so semantics are identical)
        ctxv = fluid.layers.flash_attention(q, k, v, bias_qk=attn_mask,
                                            scale=d ** -0.5)
    elif _os.environ.get("BERT_FUSED_ATTN") == "1":
        # A/B probe path: the flash_attention op with in-op dropout — on
        # TPU with FLAGS_fused_small_attention it lowers to the small-seq
        # fused kernel (bias + softmax + dropout drawn in-kernel, nothing
        # but Out/Lse ever in HBM).  MEASURED NEGATIVE in-step at the
        # flagship shape (889 vs 1081 seqs/s at bs224, r5 — the recompute
        # backward loses to XLA's materialized-probs backward), so the
        # composed emission below stays the default (BASELINE.md r5)
        ctxv = fluid.layers.flash_attention(
            q, k, v, bias_qk=attn_mask, scale=d ** -0.5,
            dropout_prob=cfg.dropout, is_test=is_test)
    else:
        # composed emission for the dropout training path: measured
        # fastest on this chip across rounds 3-5 (in-op dropout, BSHD,
        # and the round-5 Pallas small-seq kernel all landed below it)
        scores = fluid.layers.matmul(q, k, transpose_y=True,
                                     alpha=d ** -0.5)
        if attn_mask is not None:
            scores = fluid.layers.elementwise_add(scores, attn_mask)
        probs = fluid.layers.softmax(scores)
        probs = fluid.layers.dropout(
            probs, cfg.dropout, is_test=is_test,
            dropout_implementation="upscale_in_train")
        ctxv = fluid.layers.matmul(probs, v)
    ctxv = fluid.layers.transpose(ctxv, [0, 2, 1, 3])
    ctxv = fluid.layers.reshape(ctxv, [0, 0, h])
    out = fluid.layers.fc(ctxv, h, num_flatten_dims=2,
                          param_attr=_attr(prefix + "_out_w", ("model", None),
                                           use_tp))
    return out


def _epilogue(x, y, cfg, is_test):
    import os

    if os.environ.get("BERT_COMPOSED_LN") == "1":
        if cfg.dropout and not is_test:
            y = fluid.layers.dropout(
                y, cfg.dropout, is_test=is_test,
                dropout_implementation="upscale_in_train")
        return fluid.layers.layer_norm(
            fluid.layers.elementwise_add(x, y), begin_norm_axis=2)
    return fluid.layers.fused_dropout_add_ln(
        x, y, dropout_prob=cfg.dropout, is_test=is_test, begin_norm_axis=2)


def encoder_layer(x, cfg, prefix, is_test=False, use_tp=False,
                  attn_mask=None):
    attn = multi_head_attention(x, cfg, prefix + "_attn", is_test, use_tp,
                                attn_mask)
    # dropout -> residual add -> LayerNorm as ONE op: single-HBM-pass
    # Pallas kernel on TPU, mask drawn in-kernel (measured 1.82x the
    # composed emission fwd+bwd at bs256/seq128 in isolation —
    # tools/bench_fused_ln_probe.py; semantics identical).
    # BERT_COMPOSED_LN=1 restores the composed emission (A/B probe).
    x = _epilogue(x, attn, cfg, is_test)
    ffn = fluid.layers.fc(x, cfg.ffn, num_flatten_dims=2, act="gelu",
                          param_attr=_attr(prefix + "_ffn1_w",
                                           (None, "model"), use_tp))
    ffn = fluid.layers.fc(ffn, cfg.hidden, num_flatten_dims=2,
                          param_attr=_attr(prefix + "_ffn2_w",
                                           ("model", None), use_tp))
    return _epilogue(x, ffn, cfg, is_test)


def embeddings(src_ids, pos_ids, sent_ids, cfg, is_test=False):
    w = fluid.layers.embedding(src_ids, (cfg.vocab_size, cfg.hidden),
                               param_attr=ParamAttr(name="word_emb"))
    p = fluid.layers.embedding(pos_ids, (cfg.max_pos, cfg.hidden),
                               param_attr=ParamAttr(name="pos_emb"))
    s = fluid.layers.embedding(sent_ids, (cfg.type_vocab, cfg.hidden),
                               param_attr=ParamAttr(name="sent_emb"))
    emb = fluid.layers.elementwise_add(
        fluid.layers.elementwise_add(w, p), s)
    emb = fluid.layers.layer_norm(emb, begin_norm_axis=2)
    if cfg.dropout and not is_test:
        emb = fluid.layers.dropout(
            emb, cfg.dropout, is_test=is_test,
            dropout_implementation="upscale_in_train")
    return emb


def bert_encoder(cfg, seq_len, is_test=False, use_tp=False,
                 return_checkpoints=False):
    """Declare inputs + build the encoder stack; returns (inputs,
    sequence_output[, checkpoints]).  `checkpoints` are the per-layer
    outputs for RecomputeOptimizer (remat segment boundaries)."""
    src_ids = fluid.layers.data("src_ids", shape=[seq_len, 1], dtype="int64")
    pos_ids = fluid.layers.data("pos_ids", shape=[seq_len, 1], dtype="int64")
    sent_ids = fluid.layers.data("sent_ids", shape=[seq_len, 1], dtype="int64")
    input_mask = fluid.layers.data("input_mask", shape=[seq_len, 1])
    x = embeddings(src_ids, pos_ids, sent_ids, cfg, is_test)
    # attention mask: (1-m)(1-m)^T -> -1e4 where padded
    mask2d = fluid.layers.matmul(input_mask, input_mask, transpose_y=True)
    attn_mask = fluid.layers.scale(mask2d, scale=1e4, bias=-1e4)
    attn_mask = fluid.layers.unsqueeze(attn_mask, [1])  # [B,1,S,S]
    checkpoints = []
    for i in range(cfg.layers):
        x = encoder_layer(x, cfg, "layer_%d" % i, is_test, use_tp, attn_mask)
        checkpoints.append(x)
    if return_checkpoints:
        return (src_ids, pos_ids, sent_ids, input_mask), x, checkpoints
    return (src_ids, pos_ids, sent_ids, input_mask), x


def build_pretrain(cfg=BERT_BASE, seq_len=128, lr=1e-4, is_test=False,
                   use_tp=False, mask_frac=0.15):
    """Masked-LM pretraining objective (simplified: predict at mask
    positions supplied as gather indices, like the reference's
    mask_label/mask_pos feeds)."""
    inputs, seq_out = bert_encoder(cfg, seq_len, is_test, use_tp)
    mask_pos = fluid.layers.data("mask_pos", shape=[1], dtype="int64")
    mask_label = fluid.layers.data("mask_label", shape=[1], dtype="int64")
    flat = fluid.layers.reshape(seq_out, [-1, cfg.hidden])
    picked = fluid.layers.gather(flat, mask_pos)
    trans = fluid.layers.fc(picked, cfg.hidden, act="gelu")
    trans = fluid.layers.layer_norm(trans, begin_norm_axis=1)
    logits = fluid.layers.fc(trans, cfg.vocab_size)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, mask_label))
    if not is_test:
        opt = fluid.optimizer.Adam(learning_rate=lr)
        opt.minimize(loss)
    return inputs + (mask_pos, mask_label), loss
