"""YOLOv3-tiny detection model (reference analog: the yolov3 config family
served by detection ops — yolov3_loss_op.cc + yolo_box_op.cc; book-test
style train/infer builders).

Backbone: reduced darknet (conv-bn-leaky stacks with stride-2
downsampling); two detection heads at strides 32 and 16 with the standard
tiny anchor set."""

import paddle_tpu as fluid

TINY_ANCHORS = [10, 14, 23, 27, 37, 58, 81, 82, 135, 169, 344, 319]
TINY_MASKS = [[3, 4, 5], [0, 1, 2]]


def conv_bn_leaky(x, ch, ksize, stride=1, is_test=False, name=None):
    c = fluid.layers.conv2d(x, ch, ksize, stride=stride,
                            padding=(ksize - 1) // 2, bias_attr=False,
                            name=name)
    b = fluid.layers.batch_norm(c, is_test=is_test)
    return fluid.layers.leaky_relu(b, alpha=0.1)


def backbone(img, is_test=False, width=16):
    """Returns (route (stride 16), deep (stride 32)) feature maps."""
    x = conv_bn_leaky(img, width, 3, is_test=is_test)
    x = fluid.layers.pool2d(x, 2, pool_stride=2)
    x = conv_bn_leaky(x, width * 2, 3, is_test=is_test)
    x = fluid.layers.pool2d(x, 2, pool_stride=2)
    x = conv_bn_leaky(x, width * 4, 3, is_test=is_test)
    x = fluid.layers.pool2d(x, 2, pool_stride=2)
    x = conv_bn_leaky(x, width * 8, 3, is_test=is_test)
    route = fluid.layers.pool2d(x, 2, pool_stride=2)          # stride 16
    route = conv_bn_leaky(route, width * 16, 3, is_test=is_test)
    deep = fluid.layers.pool2d(route, 2, pool_stride=2)       # stride 32
    deep = conv_bn_leaky(deep, width * 32, 3, is_test=is_test)
    return route, deep


def heads(route, deep, class_num, is_test=False, width=16):
    """Two yolo heads -> list of (feature_map, anchor_mask, downsample)."""
    n_out = 3 * (5 + class_num)
    d = conv_bn_leaky(deep, width * 16, 1, is_test=is_test)
    head32 = fluid.layers.conv2d(d, n_out, 1)
    up = fluid.layers.resize_nearest(d, scale=2.0)
    cat = fluid.layers.concat([up, route], axis=1)
    c = conv_bn_leaky(cat, width * 8, 3, is_test=is_test)
    head16 = fluid.layers.conv2d(c, n_out, 1)
    return [(head32, TINY_MASKS[0], 32), (head16, TINY_MASKS[1], 16)]


def build_train(class_num=4, image_size=224, max_boxes=6, lr=1e-3,
                is_test=False, width=8):
    """Returns (img, gt_box, gt_label, loss)."""
    img = fluid.layers.data("img", shape=[3, image_size, image_size])
    gt_box = fluid.layers.data("gt_box", shape=[max_boxes, 4])
    gt_label = fluid.layers.data("gt_label", shape=[max_boxes],
                                 dtype="int32")
    route, deep = backbone(img, is_test=is_test, width=width)
    losses = []
    for fm, mask, down in heads(route, deep, class_num, is_test=is_test,
                                width=width):
        l = fluid.layers.yolov3_loss(
            fm, gt_box, gt_label, anchors=TINY_ANCHORS, anchor_mask=mask,
            class_num=class_num, ignore_thresh=0.7, downsample_ratio=down)
        losses.append(fluid.layers.mean(l))
    loss = fluid.layers.sum(losses)
    if not is_test:
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return img, gt_box, gt_label, loss


def build_infer(class_num=4, image_size=224, width=8,
                conf_thresh=0.1, nms_thresh=0.45):
    """Returns (img, im_shape, pred): pred is the multiclass_nms output
    [kept, 6] rows of (label, score, x1, y1, x2, y2), -1-padded."""
    img = fluid.layers.data("img", shape=[3, image_size, image_size])
    im_shape = fluid.layers.data("im_shape", shape=[2], dtype="int32")
    route, deep = backbone(img, is_test=True, width=width)
    all_boxes, all_scores = [], []
    for fm, mask, down in heads(route, deep, class_num, is_test=True,
                                width=width):
        anchors = []
        for m in mask:
            anchors += TINY_ANCHORS[2 * m:2 * m + 2]
        b, s = fluid.layers.yolo_box(fm, im_shape, anchors, class_num,
                                     conf_thresh, down)
        all_boxes.append(b)
        all_scores.append(fluid.layers.transpose(s, [0, 2, 1]))
    boxes = fluid.layers.concat(all_boxes, axis=1)
    scores = fluid.layers.concat(all_scores, axis=2)
    # background_label=-1: YOLO scores carry no background slot — class 0
    # is a real class (the reference yolov3 configs do the same)
    pred = fluid.layers.multiclass_nms(boxes, scores, score_threshold=0.005,
                                       nms_top_k=100, keep_top_k=50,
                                       nms_threshold=nms_thresh,
                                       background_label=-1)
    return img, im_shape, pred
