"""ResNet for ImageNet (BASELINE config 2).

Built with the fluid layer API the same way the reference's model scripts do
(conv2d + batch_norm + momentum; cf. dist_se_resnext.py test payload and the
classic fluid ResNet script).  bottleneck v1.5 architecture.
"""

import paddle_tpu as fluid

DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def conv_bn(x, filters, size, stride=1, act=None, is_test=False, name=None):
    c = fluid.layers.conv2d(
        x, filters, size, stride=stride, padding=(size - 1) // 2,
        bias_attr=False, name=name,
    )
    return fluid.layers.batch_norm(c, act=act, is_test=is_test)


def basic_block(x, filters, stride, is_test=False):
    conv0 = conv_bn(x, filters, 3, stride, act="relu", is_test=is_test)
    conv1 = conv_bn(conv0, filters, 3, 1, is_test=is_test)
    if stride != 1 or x.shape[1] != filters:
        shortcut = conv_bn(x, filters, 1, stride, is_test=is_test)
    else:
        shortcut = x
    return fluid.layers.relu(fluid.layers.elementwise_add(conv1, shortcut))


def bottleneck_block(x, filters, stride, is_test=False):
    conv0 = conv_bn(x, filters, 1, 1, act="relu", is_test=is_test)
    conv1 = conv_bn(conv0, filters, 3, stride, act="relu", is_test=is_test)
    conv2 = conv_bn(conv1, filters * 4, 1, 1, is_test=is_test)
    if stride != 1 or x.shape[1] != filters * 4:
        shortcut = conv_bn(x, filters * 4, 1, stride, is_test=is_test)
    else:
        shortcut = x
    return fluid.layers.relu(fluid.layers.elementwise_add(conv2, shortcut))


def resnet(img, class_dim=1000, depth=50, is_test=False):
    block_fn, counts = (
        (basic_block, DEPTH_CFG[depth][1])
        if DEPTH_CFG[depth][0] == "basic"
        else (bottleneck_block, DEPTH_CFG[depth][1])
    )
    x = conv_bn(img, 64, 7, 2, act="relu", is_test=is_test)
    x = fluid.layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1)
    for stage, n in enumerate(counts):
        filters = 64 * (2 ** stage)
        for i in range(n):
            stride = 2 if (i == 0 and stage > 0) else 1
            x = block_fn(x, filters, stride, is_test=is_test)
    x = fluid.layers.pool2d(x, pool_type="avg", global_pooling=True)
    logits = fluid.layers.fc(x, class_dim)
    return logits


def build_train(depth=50, class_dim=1000, image_size=224, lr=0.1,
                momentum=0.9, weight_decay=1e-4, is_test=False, amp=False):
    """Returns (img, label, loss, acc) inside the current program guard."""
    img = fluid.layers.data("img", shape=[3, image_size, image_size])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    logits = resnet(img, class_dim, depth, is_test=is_test)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
    if not is_test:
        opt = fluid.optimizer.Momentum(
            learning_rate=lr,
            momentum=momentum,
            regularization=fluid.regularizer.L2Decay(weight_decay),
        )
        if amp:
            opt = fluid.contrib.mixed_precision.decorate(opt)
        opt.minimize(loss)
    return img, label, loss, acc
