"""ResNet for ImageNet (BASELINE config 2).

Built with the fluid layer API the same way the reference's model scripts do
(conv2d + batch_norm + momentum; cf. dist_se_resnext.py test payload and the
classic fluid ResNet script).  bottleneck v1.5 architecture.
"""

import paddle_tpu as fluid

DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def conv_bn(x, filters, size, stride=1, act=None, is_test=False, name=None,
            data_format="NCHW"):
    c = fluid.layers.conv2d(
        x, filters, size, stride=stride, padding=(size - 1) // 2,
        bias_attr=False, name=name, data_format=data_format,
    )
    return fluid.layers.batch_norm(c, act=act, is_test=is_test,
                                   data_layout=data_format)


def _channels(x, data_format):
    return x.shape[1] if data_format == "NCHW" else x.shape[-1]


def basic_block(x, filters, stride, is_test=False, data_format="NCHW"):
    conv0 = conv_bn(x, filters, 3, stride, act="relu", is_test=is_test,
                    data_format=data_format)
    conv1 = conv_bn(conv0, filters, 3, 1, is_test=is_test,
                    data_format=data_format)
    if stride != 1 or _channels(x, data_format) != filters:
        shortcut = conv_bn(x, filters, 1, stride, is_test=is_test,
                           data_format=data_format)
    else:
        shortcut = x
    return fluid.layers.relu(fluid.layers.elementwise_add(conv1, shortcut))


def bottleneck_block(x, filters, stride, is_test=False, data_format="NCHW"):
    conv0 = conv_bn(x, filters, 1, 1, act="relu", is_test=is_test,
                    data_format=data_format)
    conv1 = conv_bn(conv0, filters, 3, stride, act="relu", is_test=is_test,
                    data_format=data_format)
    conv2 = conv_bn(conv1, filters * 4, 1, 1, is_test=is_test,
                    data_format=data_format)
    if stride != 1 or _channels(x, data_format) != filters * 4:
        shortcut = conv_bn(x, filters * 4, 1, stride, is_test=is_test,
                           data_format=data_format)
    else:
        shortcut = x
    return fluid.layers.relu(fluid.layers.elementwise_add(conv2, shortcut))


def resnet(img, class_dim=1000, depth=50, is_test=False, data_format="NCHW"):
    """`img` must already be in `data_format` layout."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError("data_format must be NCHW or NHWC, got %r"
                         % (data_format,))
    block_fn, counts = (
        (basic_block, DEPTH_CFG[depth][1])
        if DEPTH_CFG[depth][0] == "basic"
        else (bottleneck_block, DEPTH_CFG[depth][1])
    )
    x = conv_bn(img, 64, 7, 2, act="relu", is_test=is_test,
                data_format=data_format)
    x = fluid.layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                            data_format=data_format)
    for stage, n in enumerate(counts):
        filters = 64 * (2 ** stage)
        for i in range(n):
            stride = 2 if (i == 0 and stage > 0) else 1
            x = block_fn(x, filters, stride, is_test=is_test,
                         data_format=data_format)
    x = fluid.layers.pool2d(x, pool_type="avg", global_pooling=True,
                            data_format=data_format)
    logits = fluid.layers.fc(x, class_dim)
    return logits


def build_train(depth=50, class_dim=1000, image_size=224, lr=0.1,
                momentum=0.9, weight_decay=1e-4, is_test=False, amp=False,
                data_format="NCHW"):
    """Returns (img, label, loss, acc) inside the current program guard."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError("data_format must be NCHW or NHWC, got %r"
                         % (data_format,))
    img = fluid.layers.data("img", shape=[3, image_size, image_size])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    net_in = img
    if data_format == "NHWC":
        # feed data stays NCHW; one transpose at the boundary keeps the
        # whole network in the channels-last layout
        net_in = fluid.layers.transpose(img, [0, 2, 3, 1])
    logits = resnet(net_in, class_dim, depth, is_test=is_test,
                    data_format=data_format)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
    if not is_test:
        opt = fluid.optimizer.Momentum(
            learning_rate=lr,
            momentum=momentum,
            regularization=fluid.regularizer.L2Decay(weight_decay),
        )
        if amp:
            opt = fluid.contrib.mixed_precision.decorate(opt)
        opt.minimize(loss)
    return img, label, loss, acc
