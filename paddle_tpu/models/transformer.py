"""Transformer NMT seq2seq (BASELINE config 4 — the variable-length path).

Mirrors the reference's fluid transformer example (the model family behind
dist_transformer.py in its distributed tests): encoder-decoder with
multi-head attention, sinusoidal positions, label-smoothed CE, and
beam-search decode.  Variable-length LoD batching becomes padded dense
batches + masks (SURVEY §5); decode builds a statically-unrolled program
(each step's ops are appended at build time — XLA sees straight-line code,
the TPU-idiomatic equivalent of the reference's while_op + beam_search loop).
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.param_attr import ParamAttr

BOS, EOS = 0, 1


class TransformerConfig:
    def __init__(self, src_vocab=1000, trg_vocab=1000, d_model=64, heads=4,
                 enc_layers=2, dec_layers=2, ffn=128, max_len=64,
                 dropout=0.1, label_smooth=0.1):
        self.src_vocab = src_vocab
        self.trg_vocab = trg_vocab
        self.d_model = d_model
        self.heads = heads
        self.enc_layers = enc_layers
        self.dec_layers = dec_layers
        self.ffn = ffn
        self.max_len = max_len
        self.dropout = dropout
        self.label_smooth = label_smooth


def _pos_encoding(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype("float32")
    i = np.arange(d_model)[None, :].astype("float32")
    angle = pos / np.power(10000.0, (2 * (i // 2)) / d_model)
    enc = np.zeros((max_len, d_model), "float32")
    enc[:, 0::2] = np.sin(angle[:, 0::2])
    enc[:, 1::2] = np.cos(angle[:, 1::2])
    return enc


def _attention(q_in, kv_in, cfg, prefix, mask=None, is_test=False):
    """Multi-head attention; q_in [B, Tq, D], kv_in [B, Tk, D],
    mask broadcastable to [B, heads, Tq, Tk] additive."""
    L = fluid.layers
    D, H = cfg.d_model, cfg.heads
    dh = D // H

    def proj(x, nm):
        return L.fc(x, D, num_flatten_dims=2,
                    param_attr=ParamAttr(name=prefix + nm + "_w"),
                    bias_attr=ParamAttr(name=prefix + nm + "_b"))

    def split_heads(t, T):
        t = L.reshape(t, [-1, T, H, dh])
        return L.transpose(t, [0, 2, 1, 3])

    Tq = q_in.shape[1]
    Tk = kv_in.shape[1]
    q = split_heads(proj(q_in, "_q"), Tq)
    k = split_heads(proj(kv_in, "_k"), Tk)
    v = split_heads(proj(kv_in, "_v"), Tk)
    scores = L.matmul(q, k, transpose_y=True, alpha=dh ** -0.5)
    if mask is not None:
        scores = L.elementwise_add(scores, mask)
    attn = L.softmax(scores)
    if cfg.dropout and not is_test:
        attn = L.dropout(attn, cfg.dropout, is_test=is_test)
    out = L.matmul(attn, v)  # [B, H, Tq, dh]
    out = L.transpose(out, [0, 2, 1, 3])
    out = L.reshape(out, [-1, Tq, D])
    return L.fc(out, D, num_flatten_dims=2,
                param_attr=ParamAttr(name=prefix + "_o_w"),
                bias_attr=ParamAttr(name=prefix + "_o_b"))


def _ffn(x, cfg, prefix, is_test=False):
    L = fluid.layers
    h = L.fc(x, cfg.ffn, num_flatten_dims=2, act="relu",
             param_attr=ParamAttr(name=prefix + "_fc1_w"),
             bias_attr=ParamAttr(name=prefix + "_fc1_b"))
    if cfg.dropout and not is_test:
        h = L.dropout(h, cfg.dropout, is_test=is_test)
    return L.fc(h, cfg.d_model, num_flatten_dims=2,
                param_attr=ParamAttr(name=prefix + "_fc2_w"),
                bias_attr=ParamAttr(name=prefix + "_fc2_b"))


def _ln(x, prefix):
    return fluid.layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=ParamAttr(name=prefix + "_ln_s"),
        bias_attr=ParamAttr(name=prefix + "_ln_b"))


def _embed(ids, vocab, cfg, name, seq_len):
    L = fluid.layers
    # explicit trailing 1: fluid's lookup_table squeezes [..., 1] ids, which
    # would collapse a length-1 decode prefix ([B,1] -> [B,D])
    ids3 = L.reshape(ids, [-1, seq_len, 1])
    emb = L.embedding(ids3, size=[vocab, cfg.d_model],
                      param_attr=ParamAttr(name=name))
    emb = L.scale(emb, scale=cfg.d_model ** 0.5)
    pos = fluid.layers.tensor.assign(
        _pos_encoding(cfg.max_len, cfg.d_model)[:seq_len])
    return L.elementwise_add(emb, pos)


def encoder(src_ids, src_mask, cfg, seq_len, is_test=False):
    """src_ids [B, S] int64; src_mask [B, 1, 1, S] additive (-1e9 on pad)."""
    x = _embed(src_ids, cfg.src_vocab, cfg, "src_emb", seq_len)
    for i in range(cfg.enc_layers):
        p = "enc%d" % i
        x = _ln(x + _attention(x, x, cfg, p + "_self", src_mask,
                               is_test), p + "_att")
        x = _ln(x + _ffn(x, cfg, p, is_test), p + "_ffn")
    return x


def decoder(trg_emb, enc_out, cfg, self_mask, cross_mask, is_test=False):
    x = trg_emb
    for i in range(cfg.dec_layers):
        p = "dec%d" % i
        x = _ln(x + _attention(x, x, cfg, p + "_self", self_mask,
                               is_test), p + "_att")
        x = _ln(x + _attention(x, enc_out, cfg, p + "_cross", cross_mask,
                               is_test), p + "_cross")
        x = _ln(x + _ffn(x, cfg, p, is_test), p + "_ffn")
    return x


def _logits(dec_out, cfg):
    return fluid.layers.fc(
        dec_out, cfg.trg_vocab, num_flatten_dims=2,
        param_attr=ParamAttr(name="out_proj_w"),
        bias_attr=ParamAttr(name="out_proj_b"))


def _causal_mask(T):
    m = np.triu(np.full((T, T), -1e9, "float32"), k=1)
    return fluid.layers.tensor.assign(m.reshape(1, 1, T, T))


def _pad_mask(ids, pad_id=EOS):
    """[B, T] ids -> [B, 1, 1, T] additive mask; pad positions get -1e9.
    By convention padded source positions hold EOS."""
    L = fluid.layers
    is_pad = L.cast(L.equal(ids, L.fill_constant([1], "int64", pad_id)),
                    "float32")
    m = L.scale(is_pad, scale=-1e9)
    return L.reshape(m, [-1, 1, 1, ids.shape[1]])


def build_train(cfg, src_len, trg_len, lr=1.0, warmup=400):
    """Training graph over padded batches.  Returns (feeds, avg_loss)."""
    L = fluid.layers
    src = L.data("src_ids", shape=[-1, src_len], dtype="int64",
                 append_batch_size=False)
    trg = L.data("trg_ids", shape=[-1, trg_len], dtype="int64",
                 append_batch_size=False)
    lbl = L.data("trg_next", shape=[-1, trg_len], dtype="int64",
                 append_batch_size=False)
    weights = L.data("trg_weight", shape=[-1, trg_len], dtype="float32",
                     append_batch_size=False)

    src_mask = _pad_mask(src)
    enc_out = encoder(src, src_mask, cfg, src_len)
    trg_emb = _embed(trg, cfg.trg_vocab, cfg, "trg_emb", trg_len)
    dec_out = decoder(trg_emb, enc_out, cfg, _causal_mask(trg_len), src_mask)
    logits = _logits(dec_out, cfg)

    if cfg.label_smooth:
        one_hot = L.one_hot(L.reshape(lbl, [-1, trg_len]), cfg.trg_vocab)
        smooth = L.label_smooth(one_hot, epsilon=cfg.label_smooth)
        ce = L.softmax_with_cross_entropy(logits, smooth, soft_label=True)
    else:
        label = L.reshape(lbl, [-1, trg_len, 1])
        ce = L.softmax_with_cross_entropy(logits, label)
    ce = L.reshape(ce, [-1, trg_len])
    token_loss = L.elementwise_mul(ce, weights)
    avg_loss = L.reduce_sum(token_loss) / L.reduce_sum(weights)

    if warmup:
        sched = L.learning_rate_scheduler.noam_decay(cfg.d_model, warmup)
        if lr != 1.0:
            # lr acts as a base multiplier on the noam schedule (the
            # reference's TrainTaskConfig.learning_rate scaling)
            sched = L.scale(sched, scale=float(lr))
    else:
        sched = lr
    opt = fluid.optimizer.Adam(learning_rate=sched, beta1=0.9, beta2=0.997,
                               epsilon=1e-9)
    opt.minimize(avg_loss)
    return [src, trg, lbl, weights], avg_loss


def build_beam_infer(cfg, src_len, beam_size=4, max_out_len=None):
    """Beam-search decode graph (statically unrolled decode loop + the
    beam_search/beam_search_decode ops).  Returns (src var, seq_ids [B,K,T],
    seq_scores [B,K])."""
    L = fluid.layers
    K = beam_size
    T = max_out_len or cfg.max_len

    src = L.data("src_ids", shape=[-1, src_len], dtype="int64",
                 append_batch_size=False)
    src_mask = _pad_mask(src)
    enc_out = encoder(src, src_mask, cfg, src_len, is_test=True)

    # expand encoder state to beams: [B, S, D] -> [B*K, S, D]
    enc_k = L.expand(L.unsqueeze(enc_out, [1]), [1, K, 1, 1])
    enc_k = L.reshape(enc_k, [-1, src_len, cfg.d_model])
    srcm_k = L.expand(src_mask, [1, K, 1, 1])  # [B, K, 1, S]
    srcm_k = L.reshape(srcm_k, [-1, 1, 1, src_len])

    # alive state: prefix [B*K, t], scores [B, K]
    prefix = L.fill_constant_batch_size_like(src, [-1, 1], "int64", BOS)
    prefix = L.expand(L.reshape(prefix, [-1, 1, 1]), [1, K, 1])
    prefix = L.reshape(prefix, [-1, 1])  # [B*K, 1] of BOS
    init = np.full((1, K), -1e9, "float32")
    init[0, 0] = 0.0
    pre_scores = L.elementwise_add(
        L.fill_constant_batch_size_like(src, [-1, K], "float32", 0.0),
        fluid.layers.tensor.assign(init))
    pre_ids = L.fill_constant_batch_size_like(src, [-1, K], "int64", BOS)

    ids_array = L.create_array("int64")
    parents_array = L.create_array("int64")
    counter = L.zeros([1], "int64")

    for t in range(T):
        cur = t + 1
        trg_emb = _embed(prefix, cfg.trg_vocab, cfg, "trg_emb", cur)
        dec_out = decoder(trg_emb, enc_k, cfg, _causal_mask(cur), srcm_k,
                          is_test=True)
        last = L.slice(dec_out, axes=[1], starts=[cur - 1], ends=[cur])
        logits = _logits(last, cfg)  # [B*K, 1, V]
        logp = L.log_softmax(L.reshape(logits, [-1, K, cfg.trg_vocab]),
                             axis=-1)
        acc = L.elementwise_add(logp, pre_scores, axis=0)
        sel_ids, sel_scores, parent = L.beam_search(
            pre_ids, pre_scores, None, acc, beam_size=K, end_id=EOS)
        L.array_write(sel_ids, counter, ids_array)
        L.array_write(parent, counter, parents_array)
        counter = L.increment(counter, 1, in_place=False)

        # re-order prefixes by parent beam and append the new token
        pref3 = L.reshape(prefix, [-1, K, cur])
        new_pref = _reorder_and_append(pref3, parent, sel_ids, K, cur)
        prefix = L.reshape(new_pref, [-1, cur + 1])
        pre_scores = sel_scores
        pre_ids = sel_ids

    seq_ids, seq_scores = L.beam_search_decode(
        ids_array, parents_array, scores=pre_scores, beam_size=K, end_id=EOS)
    return src, seq_ids, seq_scores


def _reorder_and_append(pref3, parent, sel_ids, K, cur):
    """pref3 [B, K, t]; parent/sel_ids [B, K] -> [B, K, t+1]."""
    L = fluid.layers
    # one-hot matmul reorder: perm[b, k, j] = 1 where j == parent[b, k]
    onehot = L.one_hot(L.reshape(parent, [-1, K]), K)       # [B*? K, K] -> [B, K, K]
    onehot = L.reshape(onehot, [-1, K, K])
    gathered = L.matmul(onehot, L.cast(pref3, "float32"))   # [B, K, t]
    gathered = L.cast(gathered, "int64")
    return L.concat([gathered, L.reshape(sel_ids, [-1, K, 1])], axis=2)


# ---------------------------------------------------------------------------
# batching helper for the wmt16-style readers
# ---------------------------------------------------------------------------


def pad_batch(samples, src_len, trg_len):
    """samples: list of (src_ids, trg_ids, trg_next) -> padded arrays +
    per-token weights (0 on padding)."""
    n = len(samples)
    src = np.full((n, src_len), EOS, "int64")
    trg = np.full((n, trg_len), EOS, "int64")
    nxt = np.full((n, trg_len), EOS, "int64")
    w = np.zeros((n, trg_len), "float32")
    for i, (s, t, tn) in enumerate(samples):
        s = list(s)[:src_len]
        t = list(t)[:trg_len]
        tn = list(tn)[:trg_len]
        src[i, : len(s)] = s
        trg[i, : len(t)] = t
        nxt[i, : len(tn)] = tn
        w[i, : len(tn)] = 1.0
    return src, trg, nxt, w
