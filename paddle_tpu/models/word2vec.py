"""Word2vec n-gram (CBOW-style) model (reference book test:
python/paddle/fluid/tests/book/test_word2vec.py — 4 context words predict
the next word through a shared embedding)."""

import paddle_tpu as fluid


def build_train(dict_size, embed_size=32, hidden_size=64, lr=1e-3,
                is_test=False, is_sparse=False):
    """N-gram LM exactly like the book test: four context words feed one
    shared embedding table, concat -> fc -> softmax over the vocab.
    Returns (word_vars, next_word_var, avg_cost)."""
    words = [fluid.layers.data("firstw", shape=[1], dtype="int64"),
             fluid.layers.data("secondw", shape=[1], dtype="int64"),
             fluid.layers.data("thirdw", shape=[1], dtype="int64"),
             fluid.layers.data("forthw", shape=[1], dtype="int64")]
    next_word = fluid.layers.data("nextw", shape=[1], dtype="int64")

    embeds = []
    for w in words:
        e = fluid.layers.embedding(
            w, size=[dict_size, embed_size], dtype="float32",
            is_sparse=is_sparse,
            param_attr=fluid.ParamAttr(name="shared_w"))
        embeds.append(e)
    concat = fluid.layers.concat(embeds, axis=1)
    hidden = fluid.layers.fc(concat, hidden_size, act="sigmoid")
    predict = fluid.layers.fc(hidden, dict_size, act="softmax")
    cost = fluid.layers.cross_entropy(predict, next_word)
    avg_cost = fluid.layers.mean(cost)
    if not is_test:
        fluid.optimizer.SGD(learning_rate=lr).minimize(avg_cost)
    return words, next_word, avg_cost
